"""verifyImages engine (reference: pkg/engine/imageVerify.go,
imageVerifyValidate.go).

This path stays host-side by design: it is network-bound (registry +
transparency log), not compute-bound — there is no TPU work here
(SURVEY.md §7 step 7). The registry client is the plugin boundary; the
hermetic mock drives tests/CLI.
"""

from __future__ import annotations

import copy
import json
from typing import Dict, List, Optional, Tuple

from ..api.policy import Policy, Rule
from ..cosign import Options, Response, fetch_attestations, verify_signature
from ..registry.client import RegistryError
from ..utils.image import ImageInfo, image_matches
from ..utils.image_extract import extract_images_from_resource
from .api import (
    EngineResponse, PolicyContext, RuleResponse, RuleStatus, RuleType,
)
from .operators import evaluate_conditions
from .variables import substitute_all, substitute_all_in_preconditions

IMAGE_VERIFY_ANNOTATION = 'kyverno.io/verify-images'


class ImageVerificationMetadata:
    """reference: pkg/engine/imageVerifyMetadata.go"""

    def __init__(self, data: Optional[Dict[str, bool]] = None):
        self.data: Dict[str, bool] = data or {}

    def add(self, image: str, verified: bool) -> None:
        self.data[image] = verified

    def is_verified(self, image: str) -> bool:
        return self.data.get(image, False)

    def is_empty(self) -> bool:
        return not self.data

    @classmethod
    def parse(cls, annotation: str) -> 'ImageVerificationMetadata':
        return cls(json.loads(annotation))

    def annotation_patches(self, resource: dict) -> List[dict]:
        """JSONPatch ops installing the verification annotation
        (reference: imageVerifyMetadata.go Patches)."""
        if self.is_empty():
            return []
        value = json.dumps(self.data, separators=(',', ':'), sort_keys=True)
        patches = []
        meta = resource.get('metadata') or {}
        if 'annotations' not in meta:
            patches.append({'op': 'add', 'path': '/metadata/annotations',
                            'value': {}})
        key = IMAGE_VERIFY_ANNOTATION.replace('~', '~0').replace('/', '~1')
        patches.append({'op': 'add',
                        'path': f'/metadata/annotations/{key}',
                        'value': value})
        return patches


def _convert(iv: dict) -> dict:
    """Backward-compat normalization (reference:
    api/kyverno/v1/image_verification_types.go:371 Convert)."""
    if not iv.get('image') and not iv.get('key') and not iv.get('issuer'):
        return iv
    out = copy.deepcopy(iv)
    for field in ('image', 'issuer', 'subject', 'roots'):
        out.pop(field, None)
    if iv.get('image'):
        out.setdefault('imageReferences', []).append(iv['image'])
    if iv.get('annotations') or iv.get('key') or iv.get('issuer'):
        attestor: dict = {}
        if iv.get('annotations'):
            attestor['annotations'] = iv['annotations']
        if iv.get('key'):
            attestor['keys'] = {'publicKeys': iv['key']}
        elif iv.get('issuer'):
            attestor['keyless'] = {'issuer': iv['issuer'],
                                   'subject': iv.get('subject', ''),
                                   'roots': iv.get('roots', '')}
        attestor_set = {'entries': [attestor]}
        if iv.get('attestations'):
            for att in out.get('attestations') or []:
                att.setdefault('attestors', []).append(attestor_set)
        else:
            out.setdefault('attestors', []).append(attestor_set)
    return out


def _expand_static_keys(attestor_set: dict) -> dict:
    """reference: imageVerify.go:530 expandStaticKeys"""
    entries = []
    for e in attestor_set.get('entries') or []:
        keys = (e.get('keys') or {}).get('publicKeys', '')
        if keys:
            split = [k for k in
                     (s for s in _split_pem(keys)) if k.strip()]
            if len(split) > 1:
                entries.extend({'keys': {'publicKeys': k}} for k in split)
                continue
        entries.append(e)
    return {'count': attestor_set.get('count'), 'entries': entries}


def _split_pem(pem: str) -> List[str]:
    """reference: imageVerify.go:551 splitPEM"""
    marker = '-----END PUBLIC KEY-----'
    parts = pem.split(marker)
    return [p + marker for p in parts[:-1]] if len(parts) > 1 else [pem]


def _required_count(attestor_set: dict) -> int:
    """reference: imageVerify.go:574 getRequiredCount"""
    count = attestor_set.get('count')
    if not count:
        return len(attestor_set.get('entries') or [])
    return int(count)


def is_image_verified(resource: dict, image: str) -> bool:
    """reference: imageVerifyValidate.go:104 isImageVerified — raises
    ValueError when the annotation is missing/invalid."""
    if not resource:
        raise ValueError('nil resource')
    annotations = (resource.get('metadata') or {}).get('annotations') or {}
    if not annotations:
        return False
    data = annotations.get(IMAGE_VERIFY_ANNOTATION)
    if data is None:
        raise ValueError('image is not verified')
    try:
        ivm = ImageVerificationMetadata.parse(data)
    except Exception as exc:
        raise ValueError(f'failed to parse image metadata: {exc}') from exc
    return ivm.is_verified(image)


class ImageVerifier:
    """reference: pkg/engine/imageVerify.go:203 imageVerifier"""

    def __init__(self, rclient, pctx: PolicyContext, rule: Rule,
                 resp: EngineResponse, ivm: ImageVerificationMetadata):
        self.rclient = rclient
        self.pctx = pctx
        self.rule = rule
        self.resp = resp
        self.ivm = ivm

    def verify(self, image_verify: dict,
               matched_images: List[ImageInfo]) -> None:
        """reference: imageVerify.go:214 verify"""
        image_verify = _convert(image_verify)
        for info in matched_images:
            image = str(info)
            # gate every entry (incl. attestation-only) on its own
            # imageReferences: the per-rule match list is the union over
            # entries, so sibling entries' images must not leak in
            if not image_matches(image, image_verify.get('imageReferences')):
                continue
            if self._annotation_changed():
                msg = f'{IMAGE_VERIFY_ANNOTATION} annotation cannot be changed'
                self._append(RuleResponse(self.rule.name,
                                          RuleType.IMAGE_VERIFY, msg,
                                          RuleStatus.FAIL))
                continue
            try:
                if is_image_verified(self.pctx.new_resource, image):
                    continue
            except ValueError:
                pass
            # verification works on a copy: digest discovery during
            # attestor/attestation checks must not suppress the mutate-digest
            # patch (the reference passes ImageInfo by value)
            work = ImageInfo(info.registry, info.name, info.path, info.tag,
                             info.digest, info.pointer)
            rule_resp, digest = self._verify_image(image_verify, work)
            if image_verify.get('mutateDigest', True):
                rule_resp, digest = self._mutate_digest(rule_resp, digest, info)
            if rule_resp is not None:
                if image_verify.get('attestors') or \
                        image_verify.get('attestations'):
                    self.ivm.add(image, rule_resp.status == RuleStatus.PASS)
                self._append(rule_resp)

    def _append(self, rule_resp: RuleResponse) -> None:
        self.resp.policy_response.rules.append(rule_resp)
        if rule_resp.status in (RuleStatus.PASS, RuleStatus.FAIL):
            self.resp.policy_response.rules_applied_count += 1
        elif rule_resp.status == RuleStatus.ERROR:
            self.resp.policy_response.rules_error_count += 1

    def _annotation_changed(self) -> bool:
        """reference: imageVerify.go:295 hasImageVerifiedAnnotationChanged"""
        new, old = self.pctx.new_resource, self.pctx.old_resource
        if not new or not old:
            return False
        key = IMAGE_VERIFY_ANNOTATION
        get = (lambda r: ((r.get('metadata') or {}).get('annotations') or {})
               .get(key, ''))
        return get(new) != get(old)

    def _mutate_digest(self, rule_resp: Optional[RuleResponse], digest: str,
                       info: ImageInfo
                       ) -> Tuple[Optional[RuleResponse], str]:
        """reference: imageVerify.go:272 handleMutateDigest"""
        if info.digest:
            return rule_resp, digest
        if not digest:
            try:
                digest = self.rclient.fetch_image_descriptor(str(info)).digest
            except RegistryError as err:
                return (RuleResponse(
                    self.rule.name, RuleType.IMAGE_VERIFY,
                    f'failed to update digest: {err}', RuleStatus.ERROR),
                    '')
        if not digest:
            return rule_resp, digest
        patch = {'op': 'replace', 'path': info.pointer,
                 'value': f'{info}@{digest}'}
        if rule_resp is None:
            rule_resp = RuleResponse(self.rule.name, RuleType.IMAGE_VERIFY,
                                     'mutated image digest', RuleStatus.PASS)
        rule_resp.patches.append(patch)
        info.digest = digest
        return rule_resp, digest

    def _verify_image(self, image_verify: dict, info: ImageInfo
                      ) -> Tuple[Optional[RuleResponse], str]:
        """reference: imageVerify.go:324 verifyImage"""
        if not image_verify.get('attestors') and \
                not image_verify.get('attestations'):
            return None, ''
        image = str(info)
        self.pctx.json_context.add_json(
            {'image': info.to_dict() | {'reference': image}})
        if image_verify.get('attestors'):
            if not image_matches(image, image_verify.get('imageReferences')):
                return None, ''
            rule_resp, cosign_resp = self._verify_attestors(
                image_verify.get('attestors'), image_verify, info)
            if rule_resp.status != RuleStatus.PASS:
                return rule_resp, ''
            if not image_verify.get('attestations'):
                return rule_resp, cosign_resp.digest
            if not info.digest:
                info.digest = cosign_resp.digest
        return self._verify_attestations(image_verify, info)

    def _verify_attestors(self, attestors: List[dict], image_verify: dict,
                          info: ImageInfo
                          ) -> Tuple[RuleResponse, Optional[Response]]:
        """reference: imageVerify.go:374 verifyAttestors"""
        image = str(info)
        cosign_resp = None
        for attestor_set in attestors or []:
            try:
                cosign_resp = self._verify_attestor_set(
                    attestor_set, image_verify, info)
            except RegistryError as err:
                msg = f'failed to verify image {image}: {err}'
                return (RuleResponse(self.rule.name, RuleType.IMAGE_VERIFY,
                                     msg, RuleStatus.FAIL), None)
        if cosign_resp is None:
            return (RuleResponse(self.rule.name, RuleType.IMAGE_VERIFY,
                                 'invalid response: nil', RuleStatus.ERROR),
                    None)
        return (RuleResponse(self.rule.name, RuleType.IMAGE_VERIFY,
                             f'verified image signatures for {image}',
                             RuleStatus.PASS), cosign_resp)

    def _verify_attestor_set(self, attestor_set: dict, image_verify: dict,
                             info: ImageInfo) -> Response:
        """reference: imageVerify.go:479 verifyAttestorSet"""
        attestor_set = _expand_static_keys(attestor_set)
        required = _required_count(attestor_set)
        verified = 0
        errors: List[str] = []
        resp = None
        for entry in attestor_set.get('entries') or []:
            try:
                if entry.get('attestor'):
                    resp = self._verify_attestor_set(
                        entry['attestor'], image_verify, info)
                else:
                    opts = self._build_options(entry, image_verify,
                                               str(info), None)
                    resp = verify_signature(self.rclient, opts)
                verified += 1
                if verified >= required:
                    return resp
            except RegistryError as err:
                errors.append(str(err))
        raise RegistryError('; '.join(errors) or
                            f'verification failed for {info}')

    def _verify_attestations(self, image_verify: dict, info: ImageInfo
                             ) -> Tuple[RuleResponse, str]:
        """reference: imageVerify.go:414 verifyAttestations"""
        image = str(info)
        for attestation in image_verify.get('attestations') or []:
            predicate_type = attestation.get('predicateType', '')
            if not predicate_type:
                return (RuleResponse(self.rule.name, RuleType.IMAGE_VERIFY,
                                     'missing predicateType',
                                     RuleStatus.FAIL), '')
            attestors = attestation.get('attestors') or [{'entries': [{}]}]
            for attestor_set in attestors:
                required = _required_count(attestor_set)
                verified = 0
                for entry in attestor_set.get('entries') or []:
                    opts = self._build_options(entry, image_verify, image,
                                               attestation)
                    try:
                        cosign_resp = fetch_attestations(self.rclient, opts)
                    except RegistryError as err:
                        return (RuleResponse(
                            self.rule.name, RuleType.IMAGE_VERIFY,
                            f'failed to verify image {image}: {err}',
                            RuleStatus.FAIL), '')
                    if not info.digest:
                        info.digest = cosign_resp.digest
                        image = str(info)
                    err_msg = self._check_attestation_statements(
                        cosign_resp.statements, attestation, info)
                    if err_msg:
                        return (RuleResponse(
                            self.rule.name, RuleType.IMAGE_VERIFY, err_msg,
                            RuleStatus.FAIL), '')
                    verified += 1
                    if verified >= required:
                        break
                if verified < required:
                    msg = (f'image attestations verification failed, '
                           f'verifiedCount: {verified}, '
                           f'requiredCount: {required}')
                    return (RuleResponse(self.rule.name,
                                         RuleType.IMAGE_VERIFY, msg,
                                         RuleStatus.FAIL), '')
        return (RuleResponse(self.rule.name, RuleType.IMAGE_VERIFY,
                             f'verified image attestations for {image}',
                             RuleStatus.PASS), info.digest)

    def _check_attestation_statements(self, statements: List[dict],
                                      attestation: dict,
                                      info: ImageInfo) -> str:
        """reference: imageVerify.go:651 verifyAttestation"""
        predicate_type = attestation.get('predicateType', '')
        matching = [s for s in statements
                    if s.get('predicateType') == predicate_type]
        if not matching:
            return (f'attestions not found for predicate type '
                    f'{predicate_type}')
        for statement in matching:
            ok, err = self._check_attestation_conditions(attestation,
                                                         statement)
            if err:
                return f'failed to check attestations: {err}'
            if not ok:
                return (f'attestation checks failed for {info} and '
                        f'predicate {predicate_type}')
        return ''

    def _check_attestation_conditions(self, attestation: dict,
                                      statement: dict
                                      ) -> Tuple[bool, str]:
        """reference: imageVerify.go:698 checkAttestations + :709
        evaluateConditions"""
        conditions = attestation.get('conditions') or []
        if not conditions:
            return True, ''
        predicate = statement.get('predicate')
        if not isinstance(predicate, dict):
            return False, f'failed to extract predicate from statement'
        ctx = self.pctx.json_context
        ctx.checkpoint()
        try:
            ctx.add_json(predicate)
            try:
                substituted = substitute_all(ctx, conditions)
            except Exception as exc:  # noqa: BLE001
                return False, f'failed to substitute variables: {exc}'
            return (all(evaluate_conditions(ctx, c) for c in substituted),
                    '')
        finally:
            ctx.restore()

    def _build_options(self, attestor: dict, image_verify: dict, image: str,
                       attestation: Optional[dict]) -> Options:
        """reference: imageVerify.go:582 buildOptionsAndPath"""
        keys = attestor.get('keys') or {}
        keyless = attestor.get('keyless') or {}
        certs = attestor.get('certificates') or {}
        # every attestor flavor may carry a rekor block
        # (image_verification_types.go:149,173,181); nil → not checked
        rekor = keys.get('rekor') or certs.get('rekor') or \
            keyless.get('rekor') or {}
        return Options(
            image_ref=image,
            key=(keys.get('publicKeys') or '').strip(),
            cert=certs.get('cert', ''),
            cert_chain=certs.get('certChain', ''),
            roots=keyless.get('roots', ''),
            subject=keyless.get('subject', ''),
            issuer=keyless.get('issuer', ''),
            annotations=attestor.get('annotations') or {},
            repository=(attestor.get('repository')
                        or image_verify.get('repository', '')),
            rekor_url=rekor.get('url', ''),
            rekor_pubkey=rekor.get('pubkey', ''),
            ignore_tlog=bool(rekor.get('ignoreTlog', False)),
            predicate_type=(attestation or {}).get('predicateType', ''),
            fetch_attestations=attestation is not None,
        )


def get_matching_images(pctx: PolicyContext, rule: Rule
                        ) -> Tuple[List[ImageInfo], str]:
    """reference: imageVerify.go:50 extractMatchingImages"""
    infos = extract_images_from_resource(
        pctx.new_resource, rule.raw.get('imageExtractors'))
    all_infos = [info for group in infos.values() for info in group.values()]
    refs = []
    matched = []
    for iv in rule.verify_images:
        iv = _convert(iv)
        patterns = iv.get('imageReferences') or []
        refs.extend(patterns)
        for info in all_infos:
            if image_matches(str(info), patterns):
                matched.append(info)
    return matched, ','.join(refs)


def verify_and_patch_images(engine, pctx: PolicyContext, rclient
                            ) -> Tuple[EngineResponse,
                                       ImageVerificationMetadata]:
    """reference: pkg/engine/imageVerify.go:69 VerifyAndPatchImages"""
    import time
    start = time.time()
    resp = EngineResponse(pctx.policy)
    ivm = ImageVerificationMetadata()
    policy = pctx.policy
    apply_rules = policy.apply_rules
    ctx = pctx.json_context
    _add_resource_images(pctx)
    ctx.checkpoint()
    try:
        for raw_rule in engine._compute_rules(policy):
            rule = Rule(raw_rule)
            if not rule.verify_images:
                continue
            if not engine._matches(rule, pctx):
                continue
            exception_resp = engine._check_exceptions(pctx, rule)
            if exception_resp is not None:
                resp.policy_response.rules.append(exception_resp)
                continue
            matched, refs = _matching_or_error(pctx, rule, resp)
            if matched is None:
                continue
            if not matched:
                resp.policy_response.rules.append(RuleResponse(
                    rule.name, RuleType.IMAGE_VERIFY,
                    f"skip run verification as image in resource not "
                    f"found in imageRefs '{refs}'", RuleStatus.SKIP))
                continue
            ctx.reset()
            try:
                engine.context_loader.load(rule.context, ctx,
                                           policy_name=pctx.policy.name,
                                           rule_name=rule.name)
            except Exception as exc:  # noqa: BLE001
                resp.policy_response.rules.append(RuleResponse(
                    rule.name, RuleType.IMAGE_VERIFY,
                    f'failed to load context: {exc}', RuleStatus.ERROR))
                continue
            try:
                substituted = _substitute_rule_variables(ctx, raw_rule)
            except Exception as exc:  # noqa: BLE001
                resp.policy_response.rules.append(RuleResponse(
                    rule.name, RuleType.IMAGE_VERIFY,
                    f'failed to substitute variables: {exc}',
                    RuleStatus.ERROR))
                continue
            if rclient is None:
                resp.policy_response.rules.append(RuleResponse(
                    rule.name, RuleType.IMAGE_VERIFY,
                    'image verification requires a registry client',
                    RuleStatus.ERROR))
                continue
            verifier = ImageVerifier(rclient, pctx, substituted, resp, ivm)
            for image_verify in substituted.verify_images:
                verifier.verify(image_verify, matched)
            if apply_rules == 'One' and \
                    resp.policy_response.rules_applied_count > 0:
                break
    finally:
        ctx.restore()
    engine._build_response(pctx, resp, start)
    return resp, ivm


def _substitute_rule_variables(ctx, raw_rule: dict) -> Rule:
    """Substitute variables everywhere except attestations, whose
    conditions resolve against each statement's predicate at check time
    (reference: imageVerify.go:182 substituteVariables)."""
    rule_copy = copy.deepcopy(raw_rule)
    saved = []
    for iv in rule_copy.get('verifyImages') or []:
        saved.append(copy.deepcopy(iv.get('attestations')))
        iv.pop('attestations', None)
    rule_copy = substitute_all(ctx, rule_copy)
    for iv, attestations in zip(rule_copy.get('verifyImages') or [], saved):
        if attestations is not None:
            iv['attestations'] = attestations
    return Rule(rule_copy)


def _matching_or_error(pctx, rule, resp):
    try:
        return get_matching_images(pctx, rule)
    except Exception as exc:  # noqa: BLE001
        resp.policy_response.rules.append(RuleResponse(
            rule.name, RuleType.IMAGE_VERIFY,
            f'failed to extract images: {exc}', RuleStatus.ERROR))
        return None, ''


def _add_resource_images(pctx: PolicyContext) -> None:
    try:
        infos = extract_images_from_resource(pctx.new_resource)
    except Exception:  # noqa: BLE001 — kinds without extractors
        return
    if infos:
        pctx.json_context.add_image_infos(
            {name: {k: i.to_dict() for k, i in group.items()}
             for name, group in infos.items()})


def process_image_validation_rule(engine, pctx: PolicyContext,
                                  rule: Rule) -> Optional[RuleResponse]:
    """Audit/background validate-mode check of verifyImages rules against
    the kyverno.io/verify-images annotation
    (reference: pkg/engine/imageVerifyValidate.go:18
    processImageValidationRule)."""
    try:
        matched, _ = get_matching_images(pctx, rule)
    except Exception as exc:  # noqa: BLE001
        return RuleResponse(rule.name, RuleType.VALIDATION, str(exc),
                            RuleStatus.ERROR)
    if not matched:
        return RuleResponse(rule.name, RuleType.VALIDATION, 'image verified',
                            RuleStatus.SKIP)
    ctx = pctx.json_context
    try:
        engine.context_loader.load(rule.context, ctx,
                                   policy_name=pctx.policy.name,
                                   rule_name=rule.name)
    except Exception as exc:  # noqa: BLE001
        return RuleResponse(rule.name, RuleType.VALIDATION,
                            f'failed to load context: {exc}',
                            RuleStatus.ERROR)
    try:
        conditions = substitute_all_in_preconditions(ctx, rule.preconditions)
    except Exception as exc:  # noqa: BLE001
        return RuleResponse(rule.name, RuleType.VALIDATION,
                            f'failed to evaluate preconditions: {exc}',
                            RuleStatus.ERROR)
    if conditions is not None and not evaluate_conditions(ctx, conditions):
        return RuleResponse(rule.name, RuleType.VALIDATION,
                            'preconditions not met', RuleStatus.SKIP)
    for iv in rule.verify_images:
        image_verify = _convert(iv)
        for info in matched:
            image = str(info)
            if not image_matches(image, image_verify.get('imageReferences')):
                continue
            if image_verify.get('verifyDigest', True) and not info.digest:
                return RuleResponse(rule.name, RuleType.IMAGE_VERIFY,
                                    f'missing digest for {image}',
                                    RuleStatus.FAIL)
            if image_verify.get('required', True) and pctx.new_resource:
                try:
                    verified = is_image_verified(pctx.new_resource, image)
                except ValueError as err:
                    return RuleResponse(rule.name, RuleType.IMAGE_VERIFY,
                                        str(err), RuleStatus.FAIL)
                if not verified:
                    return RuleResponse(rule.name, RuleType.IMAGE_VERIFY,
                                        f'unverified image {image}',
                                        RuleStatus.FAIL)
    return RuleResponse(rule.name, RuleType.VALIDATION, 'image verified',
                        RuleStatus.PASS)
