"""Pod Security Standards check library.

Native implementation of the upstream k8s.io/pod-security-admission
``policy.DefaultChecks()`` set that the reference wraps
(reference: pkg/pss/evaluate.go:17 evaluatePSS). Checks operate on
unstructured pod dicts {metadata, spec}. Latest-version semantics.

Each check returns a CheckResult; failing results carry the upstream-style
forbidden reason/detail strings.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

LEVEL_BASELINE = 'baseline'
LEVEL_RESTRICTED = 'restricted'


class CheckResult(NamedTuple):
    allowed: bool
    forbidden_reason: str = ''
    forbidden_detail: str = ''


class Check(NamedTuple):
    id: str
    level: str
    fn: Callable[[dict, dict], CheckResult]
    # upstream checks carry one implementation per MinimumVersion, and
    # the reference runs EVERY versioned variant regardless of the
    # requested version (pkg/pss/evaluate.go:24 `for _, versionCheck :=
    # range check.Versions` — no dedup), so a pod failing two variants
    # reports the violation twice.  Empty → just ``fn``.
    fns: tuple = ()


OK = CheckResult(True)


def _containers(spec: dict, include_init=True, include_ephemeral=True):
    out = []
    for c in spec.get('containers') or []:
        out.append(c)
    if include_init:
        out.extend(spec.get('initContainers') or [])
    if include_ephemeral:
        out.extend(spec.get('ephemeralContainers') or [])
    return out


def _pluralize(singular: str, plural: str, n: int) -> str:
    return singular if n == 1 else plural


def _join_quote(names: List[str]) -> str:
    return ', '.join(f'"{n}"' for n in names)


def _sec_ctx(obj: dict) -> dict:
    return obj.get('securityContext') or {}


# -- baseline ----------------------------------------------------------------

def check_host_namespaces(meta: dict, spec: dict) -> CheckResult:
    fields = []
    if spec.get('hostNetwork'):
        fields.append('hostNetwork=true')
    if spec.get('hostPID'):
        fields.append('hostPID=true')
    if spec.get('hostIPC'):
        fields.append('hostIPC=true')
    if fields:
        return CheckResult(False, 'host namespaces', ', '.join(fields))
    return OK


def check_privileged(meta: dict, spec: dict) -> CheckResult:
    bad = [c.get('name', '') for c in _containers(spec)
           if _sec_ctx(c).get('privileged') is True]
    if bad:
        return CheckResult(
            False, 'privileged',
            f'{_pluralize("container", "containers", len(bad))} '
            f'{_join_quote(bad)} must not set securityContext.privileged=true')
    return OK


_BASELINE_CAPS = {
    'AUDIT_WRITE', 'CHOWN', 'DAC_OVERRIDE', 'FOWNER', 'FSETID', 'KILL',
    'MKNOD', 'NET_BIND_SERVICE', 'SETFCAP', 'SETGID', 'SETPCAP', 'SETUID',
    'SYS_CHROOT',
}


def check_capabilities_baseline(meta: dict, spec: dict) -> CheckResult:
    bad: Dict[str, List[str]] = {}
    forbidden = set()
    for c in _containers(spec):
        caps = (_sec_ctx(c).get('capabilities') or {}).get('add') or []
        non_default = [cap for cap in caps if cap not in _BASELINE_CAPS]
        if non_default:
            bad[c.get('name', '')] = non_default
            forbidden.update(non_default)
    if bad:
        return CheckResult(
            False, 'non-default capabilities',
            f'{_pluralize("container", "containers", len(bad))} '
            f'{_join_quote(list(bad))} must not include '
            f'{_join_quote(sorted(forbidden))} in '
            f'securityContext.capabilities.add')
    return OK


def check_host_path_volumes(meta: dict, spec: dict) -> CheckResult:
    bad = [v.get('name', '') for v in spec.get('volumes') or []
           if 'hostPath' in v]
    if bad:
        return CheckResult(
            False, 'hostPath volumes',
            f'{_pluralize("volume", "volumes", len(bad))} {_join_quote(bad)}')
    return OK


def check_host_ports(meta: dict, spec: dict) -> CheckResult:
    bad: Dict[str, List[int]] = {}
    ports = set()
    for c in _containers(spec):
        host_ports = [p.get('hostPort') for p in c.get('ports') or []
                      if p.get('hostPort')]
        if host_ports:
            bad[c.get('name', '')] = host_ports
            ports.update(host_ports)
    if bad:
        return CheckResult(
            False, 'hostPort',
            f'{_pluralize("container", "containers", len(bad))} '
            f'{_join_quote(list(bad))} '
            f'{_pluralize("uses", "use", len(bad))} '
            f'{_pluralize("hostPort", "hostPorts", len(ports))} '
            f'{", ".join(str(p) for p in sorted(ports))}')
    return OK


_APPARMOR_PREFIX = 'container.apparmor.security.beta.kubernetes.io/'


def check_app_armor(meta: dict, spec: dict) -> CheckResult:
    bad = []
    for k, v in (meta.get('annotations') or {}).items():
        if k.startswith(_APPARMOR_PREFIX):
            if v not in ('runtime/default', '') and not str(v).startswith('localhost/'):
                bad.append(f'{k}="{v}"')
    if bad:
        return CheckResult(
            False, 'forbidden AppArmor profile',
            f'{_pluralize("annotation", "annotations", len(bad))} '
            f'{", ".join(sorted(bad))}')
    return OK


_ALLOWED_SELINUX_TYPES = {'', 'container_t', 'container_init_t', 'container_kvm_t'}


def check_selinux_options(meta: dict, spec: dict) -> CheckResult:
    bad_types = set()
    bad_user_role = False
    scopes = [('pod', _sec_ctx(spec))]
    scopes += [(f'container "{c.get("name", "")}"', _sec_ctx(c))
               for c in _containers(spec)]
    for _, sc in scopes:
        opts = sc.get('seLinuxOptions') or {}
        t = opts.get('type', '')
        if t not in _ALLOWED_SELINUX_TYPES:
            bad_types.add(t)
        if opts.get('user') or opts.get('role'):
            bad_user_role = True
    details = []
    if bad_types:
        details.append(
            f'{_pluralize("type", "types", len(bad_types))} '
            f'{_join_quote(sorted(bad_types))}')
    if bad_user_role:
        details.append('user or role')
    if details:
        return CheckResult(False, 'seLinuxOptions', '; '.join(details))
    return OK


def check_proc_mount(meta: dict, spec: dict) -> CheckResult:
    bad: Dict[str, str] = {}
    for c in _containers(spec):
        pm = _sec_ctx(c).get('procMount')
        if pm and pm != 'Default':
            bad[c.get('name', '')] = pm
    if bad:
        return CheckResult(
            False, 'procMount',
            f'{_pluralize("container", "containers", len(bad))} '
            f'{_join_quote(list(bad))} must not set securityContext.procMount '
            f'to {_join_quote(sorted(set(bad.values())))}')
    return OK


def check_seccomp_baseline(meta: dict, spec: dict) -> CheckResult:
    bad = []
    pod_type = (_sec_ctx(spec).get('seccompProfile') or {}).get('type')
    if pod_type == 'Unconfined':
        bad.append('pod must not set securityContext.seccompProfile.type to '
                   '"Unconfined"')
    bad_containers = [
        c.get('name', '') for c in _containers(spec)
        if (_sec_ctx(c).get('seccompProfile') or {}).get('type') == 'Unconfined']
    if bad_containers:
        bad.append(
            f'{_pluralize("container", "containers", len(bad_containers))} '
            f'{_join_quote(bad_containers)} must not set '
            f'securityContext.seccompProfile.type to "Unconfined"')
    if bad:
        return CheckResult(False, 'seccompProfile', '; '.join(bad))
    return OK


_ALLOWED_SYSCTLS = {
    'kernel.shm_rmid_forced', 'net.ipv4.ip_local_port_range',
    'net.ipv4.ip_unprivileged_port_start', 'net.ipv4.tcp_syncookies',
    'net.ipv4.ping_group_range',
}


def check_sysctls(meta: dict, spec: dict) -> CheckResult:
    bad = [s.get('name', '') for s in _sec_ctx(spec).get('sysctls') or []
           if s.get('name', '') not in _ALLOWED_SYSCTLS]
    if bad:
        return CheckResult(
            False, 'forbidden sysctls',
            _join_quote(sorted(bad)))
    return OK


def check_windows_host_process(meta: dict, spec: dict) -> CheckResult:
    bad = []
    pod_wo = (_sec_ctx(spec).get('windowsOptions') or {})
    if pod_wo.get('hostProcess') is True:
        bad.append('pod')
    bad_containers = [
        c.get('name', '') for c in _containers(spec)
        if (_sec_ctx(c).get('windowsOptions') or {}).get('hostProcess') is True]
    if bad or bad_containers:
        parts = []
        if bad:
            parts.append('pod must not set '
                         'securityContext.windowsOptions.hostProcess=true')
        if bad_containers:
            parts.append(
                f'{_pluralize("container", "containers", len(bad_containers))} '
                f'{_join_quote(bad_containers)} must not set '
                f'securityContext.windowsOptions.hostProcess=true')
        return CheckResult(False, 'hostProcess', '; '.join(parts))
    return OK


# -- restricted --------------------------------------------------------------

_ALLOWED_VOLUME_TYPES = {
    'configMap', 'csi', 'downwardAPI', 'emptyDir', 'ephemeral',
    'persistentVolumeClaim', 'projected', 'secret',
}


def check_restricted_volumes(meta: dict, spec: dict) -> CheckResult:
    bad = []
    bad_types = set()
    for v in spec.get('volumes') or []:
        types = [k for k in v if k != 'name']
        restricted = [t for t in types if t not in _ALLOWED_VOLUME_TYPES]
        if restricted:
            bad.append(v.get('name', ''))
            bad_types.update(restricted)
    if bad:
        return CheckResult(
            False, 'restricted volume types',
            f'{_pluralize("volume", "volumes", len(bad))} {_join_quote(bad)} '
            f'{_pluralize("uses", "use", len(bad))} restricted volume '
            f'{_pluralize("type", "types", len(bad_types))} '
            f'{_join_quote(sorted(bad_types))}')
    return OK


def check_allow_privilege_escalation(meta: dict, spec: dict) -> CheckResult:
    bad = [c.get('name', '') for c in _containers(spec)
           if _sec_ctx(c).get('allowPrivilegeEscalation') is not False]
    if bad:
        return CheckResult(
            False, 'allowPrivilegeEscalation != false',
            f'{_pluralize("container", "containers", len(bad))} '
            f'{_join_quote(bad)} must set '
            f'securityContext.allowPrivilegeEscalation=false')
    return OK


def check_run_as_non_root(meta: dict, spec: dict) -> CheckResult:
    pod_non_root = _sec_ctx(spec).get('runAsNonRoot')
    bad = []
    explicitly_bad = []
    for c in _containers(spec):
        c_setting = _sec_ctx(c).get('runAsNonRoot')
        if c_setting is False:
            explicitly_bad.append(c.get('name', ''))
        elif c_setting is None and pod_non_root is not True:
            bad.append(c.get('name', ''))
    details = []
    if pod_non_root is False:
        details.append('pod must not set securityContext.runAsNonRoot=false')
    if explicitly_bad:
        details.append(
            f'{_pluralize("container", "containers", len(explicitly_bad))} '
            f'{_join_quote(explicitly_bad)} must not set '
            f'securityContext.runAsNonRoot=false')
    if bad and pod_non_root is not True:
        details.append(
            f'pod or {_pluralize("container", "containers", len(bad))} '
            f'{_join_quote(bad)} must set securityContext.runAsNonRoot=true')
    if details:
        return CheckResult(False, 'runAsNonRoot != true', '; '.join(details))
    return OK


def check_run_as_user(meta: dict, spec: dict) -> CheckResult:
    details = []
    if _sec_ctx(spec).get('runAsUser') == 0:
        details.append('pod must not set runAsUser=0')
    bad = [c.get('name', '') for c in _containers(spec)
           if _sec_ctx(c).get('runAsUser') == 0]
    if bad:
        details.append(
            f'{_pluralize("container", "containers", len(bad))} '
            f'{_join_quote(bad)} must not set runAsUser=0')
    if details:
        return CheckResult(False, 'runAsUser=0', '; '.join(details))
    return OK


def check_seccomp_restricted(meta: dict, spec: dict) -> CheckResult:
    pod_type = (_sec_ctx(spec).get('seccompProfile') or {}).get('type')
    pod_ok = pod_type in ('RuntimeDefault', 'Localhost')
    bad = []
    explicitly_bad = []
    for c in _containers(spec):
        c_type = (_sec_ctx(c).get('seccompProfile') or {}).get('type')
        if c_type in ('RuntimeDefault', 'Localhost'):
            continue
        if c_type is None:
            if not pod_ok:
                bad.append(c.get('name', ''))
        else:
            explicitly_bad.append(c.get('name', ''))
    details = []
    if explicitly_bad:
        details.append(
            f'{_pluralize("container", "containers", len(explicitly_bad))} '
            f'{_join_quote(explicitly_bad)} must not set '
            f'securityContext.seccompProfile.type to "Unconfined"')
    if bad:
        details.append(
            f'pod or {_pluralize("container", "containers", len(bad))} '
            f'{_join_quote(bad)} must set securityContext.seccompProfile.type '
            f'to "RuntimeDefault" or "Localhost"')
    if details:
        return CheckResult(False, 'seccompProfile', '; '.join(details))
    return OK


def check_capabilities_restricted(meta: dict, spec: dict) -> CheckResult:
    bad_drop = []
    bad_add: Dict[str, List[str]] = {}
    forbidden = set()
    for c in _containers(spec, include_ephemeral=False):
        caps = _sec_ctx(c).get('capabilities') or {}
        drop = caps.get('drop') or []
        if 'ALL' not in drop:
            bad_drop.append(c.get('name', ''))
        add = [cap for cap in caps.get('add') or []
               if cap != 'NET_BIND_SERVICE']
        if add:
            bad_add[c.get('name', '')] = add
            forbidden.update(add)
    details = []
    if bad_drop:
        details.append(
            f'{_pluralize("container", "containers", len(bad_drop))} '
            f'{_join_quote(bad_drop)} must set '
            f'securityContext.capabilities.drop=["ALL"]')
    if bad_add:
        details.append(
            f'{_pluralize("container", "containers", len(bad_add))} '
            f'{_join_quote(list(bad_add))} must not include '
            f'{_join_quote(sorted(forbidden))} in '
            f'securityContext.capabilities.add')
    if details:
        return CheckResult(False, 'unrestricted capabilities',
                           '; '.join(details))
    return OK


def _windows_exempt(fn: Callable[[dict, dict], CheckResult]
                    ) -> Callable[[dict, dict], CheckResult]:
    """The 1.25 variants skip linux-only checks for windows pods
    (KEP-2802: pod.spec.os.name == 'windows')."""
    def variant(meta: dict, spec: dict) -> CheckResult:
        if (spec.get('os') or {}).get('name') == 'windows':
            return OK
        return fn(meta, spec)
    return variant


_SECCOMP_ANNOTATION_POD = 'seccomp.security.alpha.kubernetes.io/pod'
_SECCOMP_ANNOTATION_PREFIX = 'container.seccomp.security.alpha.kubernetes.io/'


def check_seccomp_baseline_1_0(meta: dict, spec: dict) -> CheckResult:
    """The pre-1.19 annotation-based seccomp check
    (pod-security-admission check_seccompProfile_baseline.go v1.0)."""
    annotations = meta.get('annotations') or {}
    forbidden = []
    val = annotations.get(_SECCOMP_ANNOTATION_POD)
    if val == 'unconfined':
        forbidden.append(f'{_SECCOMP_ANNOTATION_POD}="{val}"')
    for c in _containers(spec):
        key = _SECCOMP_ANNOTATION_PREFIX + c.get('name', '')
        val = annotations.get(key)
        if val == 'unconfined':
            forbidden.append(f'{key}="{val}"')
    if forbidden:
        return CheckResult(
            False, 'seccompProfile',
            f'forbidden '
            f'{_pluralize("annotation", "annotations", len(forbidden))} '
            f'{", ".join(forbidden)}')
    return OK


DEFAULT_CHECKS: List[Check] = [
    Check('hostNamespaces', LEVEL_BASELINE, check_host_namespaces),
    Check('privileged', LEVEL_BASELINE, check_privileged),
    Check('capabilities_baseline', LEVEL_BASELINE, check_capabilities_baseline),
    Check('hostPathVolumes', LEVEL_BASELINE, check_host_path_volumes),
    Check('hostPorts', LEVEL_BASELINE, check_host_ports),
    Check('appArmorProfile', LEVEL_BASELINE, check_app_armor),
    Check('seLinuxOptions', LEVEL_BASELINE, check_selinux_options),
    Check('procMount', LEVEL_BASELINE, check_proc_mount),
    Check('seccompProfile_baseline', LEVEL_BASELINE, check_seccomp_baseline,
          (check_seccomp_baseline_1_0, check_seccomp_baseline)),
    Check('sysctls', LEVEL_BASELINE, check_sysctls),
    Check('windowsHostProcess', LEVEL_BASELINE, check_windows_host_process),
    Check('restrictedVolumes', LEVEL_RESTRICTED, check_restricted_volumes),
    Check('allowPrivilegeEscalation', LEVEL_RESTRICTED,
          check_allow_privilege_escalation,
          (check_allow_privilege_escalation,
           _windows_exempt(check_allow_privilege_escalation))),
    Check('runAsNonRoot', LEVEL_RESTRICTED, check_run_as_non_root),
    Check('runAsUser', LEVEL_RESTRICTED, check_run_as_user),
    Check('seccompProfile_restricted', LEVEL_RESTRICTED,
          check_seccomp_restricted,
          (check_seccomp_restricted,
           _windows_exempt(check_seccomp_restricted))),
    Check('capabilities_restricted', LEVEL_RESTRICTED,
          check_capabilities_restricted,
          (check_capabilities_restricted,
           _windows_exempt(check_capabilities_restricted))),
]


# Control name → check ids (reference: pkg/pss/utils/mapping.go:45)
PSS_CONTROLS_TO_CHECK_ID: Dict[str, List[str]] = {
    'Capabilities': ['capabilities_baseline', 'capabilities_restricted'],
    'Seccomp': ['seccompProfile_baseline', 'seccompProfile_restricted'],
    'Privileged Containers': ['privileged'],
    'Host Ports': ['hostPorts'],
    '/proc Mount Type': ['procMount'],
    'HostProcess': ['windowsHostProcess'],
    'SELinux': ['seLinuxOptions'],
    'Host Namespaces': ['hostNamespaces'],
    'HostPath Volumes': ['hostPathVolumes'],
    'Sysctls': ['sysctls'],
    'AppArmor': ['appArmorProfile'],
    'Volume Types': ['restrictedVolumes'],
    'Privilege Escalation': ['allowPrivilegeEscalation'],
    'Running as Non-root': ['runAsNonRoot'],
    'Running as Non-root user': ['runAsUser'],
}
