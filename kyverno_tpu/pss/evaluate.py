"""PSS evaluation with Kyverno exclusion semantics.

Re-implements the reference's EvaluatePod
(reference: pkg/pss/evaluate.go:84): run the check set for the rule's
level/version, then exempt failing check ids matched by the rule's
``exclude`` entries (pod-level when no images are given, else only the
containers whose images match).
"""

from __future__ import annotations

import copy
import re
from typing import Any, Dict, List, Optional, Tuple

from ..utils import wildcard
from .checks import (DEFAULT_CHECKS, LEVEL_BASELINE, PSS_CONTROLS_TO_CHECK_ID,
                     CheckResult)

_VERSION_RE = re.compile(r'^v?(\d+)\.(\d+)$')


def parse_version(rule: dict) -> Tuple[str, str]:
    level = rule.get('level', '') or ''
    version = rule.get('version', '') or ''
    if version in ('', 'latest'):
        version = 'latest'
    elif not _VERSION_RE.match(version):
        raise ValueError(f'invalid pod security admission version {version!r}')
    return level, version


def evaluate_pss(level: str, pod: dict) -> List[dict]:
    """Run the default checks and return failing results
    (reference: pkg/pss/evaluate.go:17 evaluatePSS)."""
    meta = pod.get('metadata') or {}
    spec = pod.get('spec') or {}
    results = []
    for check in DEFAULT_CHECKS:
        if level == LEVEL_BASELINE and check.level != level:
            continue
        # EVERY versioned variant runs, regardless of the requested
        # version, and failing variants each append a result — the
        # reference does not dedup (evaluate.go:24-35), so a pod
        # failing two variants reports the violation twice
        for variant in (check.fns or (check.fn,)):
            result = variant(meta, spec)
            if not result.allowed:
                results.append({
                    'id': check.id,
                    'checkResult': {
                        'allowed': False,
                        'forbiddenReason': result.forbidden_reason,
                        'forbiddenDetail': result.forbidden_detail,
                    },
                })
    return results


def evaluate_pod_security(rule: dict, pod: dict) -> Tuple[bool, List[dict]]:
    """reference: pkg/pss/evaluate.go:84 EvaluatePod"""
    level, _version = parse_version(rule)
    default_results = evaluate_pss(level, pod)
    for exclude in rule.get('exclude') or []:
        pod_level, matching = _pod_with_matching_containers(exclude, pod)
        target = pod_level if pod_level is not None else matching
        exclude_results = evaluate_pss(level, target)
        default_results = _exempt(default_results, exclude_results, exclude)
    return len(default_results) == 0, default_results


def _pod_with_matching_containers(exclude: dict, pod: dict):
    # reference: pkg/pss/evaluate.go:110 GetPodWithMatchingContainers
    images = exclude.get('images') or []
    if not images:
        pod_copy = copy.deepcopy(pod)
        spec = pod_copy.setdefault('spec', {})
        spec['containers'] = [{'name': 'fake'}]
        spec.pop('initContainers', None)
        spec.pop('ephemeralContainers', None)
        return pod_copy, None
    meta = pod.get('metadata') or {}
    matching = {'metadata': {'name': meta.get('name', ''),
                             'namespace': meta.get('namespace', '')},
                'spec': {}}
    spec = pod.get('spec') or {}
    for field in ('containers', 'initContainers', 'ephemeralContainers'):
        selected = [c for c in spec.get(field) or []
                    if wildcard.check_patterns(images, c.get('image', ''))]
        if selected:
            matching['spec'][field] = selected
    return None, matching


def _exempt(default_results: List[dict], exclude_results: List[dict],
            exclude: dict) -> List[dict]:
    # reference: pkg/pss/evaluate.go:38 exemptKyvernoExclusion — the
    # results round-trip through a map keyed by check ID, so duplicate
    # versioned-variant results COLLAPSE whenever a rule has excludes
    # (last one wins); insertion order stands in for Go's random map
    # iteration
    by_id = {}
    for r in default_results:
        by_id[r['id']] = r
    check_ids = PSS_CONTROLS_TO_CHECK_ID.get(exclude.get('controlName', ''), [])
    for ex in exclude_results:
        if ex['id'] in check_ids:
            by_id.pop(ex['id'], None)
    return list(by_id.values())


def format_checks_print(checks: List[dict]) -> str:
    """Go-style %+v print of the failing checks
    (reference: pkg/pss/evaluate.go:160 FormatChecksPrint)."""
    out = ''
    for check in checks:
        cr = check['checkResult']
        out += (f"({{Allowed:{str(cr['allowed']).lower()} "
                f"ForbiddenReason:{cr['forbiddenReason']} "
                f"ForbiddenDetail:{cr['forbiddenDetail']}}})\n")
    return out


_TEMPLATE_KINDS = {'DaemonSet', 'Deployment', 'Job', 'StatefulSet',
                   'ReplicaSet', 'ReplicationController'}


def extract_pod_spec(resource: dict) -> dict:
    """Extract a pod {metadata, spec} from one of the 8 workload kinds
    (reference: pkg/engine/validation.go:481 getSpec)."""
    kind = resource.get('kind', '')
    if kind in _TEMPLATE_KINDS:
        template = ((resource.get('spec') or {}).get('template') or {})
        return {'metadata': template.get('metadata') or {},
                'spec': template.get('spec') or {}}
    if kind == 'CronJob':
        template = (((resource.get('spec') or {}).get('jobTemplate') or {})
                    .get('spec') or {}).get('template') or {}
        return {'metadata': template.get('metadata') or {},
                'spec': template.get('spec') or {}}
    if kind == 'Pod':
        return {'metadata': resource.get('metadata') or {},
                'spec': resource.get('spec') or {}}
    raise ValueError(f'unsupported kind {kind!r} for podSecurity rule')
