"""Qualified-name interprocedural engine: call graph + taint lattice.

The trace-safety (KTPU1xx), retrace (KTPU2xx), and concurrency
(KTPU6xx) passes share two whole-program questions this module
answers:

1. *Could this function's body execute inside ``jax.jit``* (or on a
   ``threading.Thread``)?  — reachability over a **resolved** call
   graph.
2. *Does this value derive from a traced argument?* — a param-rooted
   **taint lattice** over that graph.

**Binder (two passes).**  Pass one indexes every module in a single
tree traversal: defs by name, classes with their methods, import
aliases (relative imports resolved against the importing package),
parent links, and *assignment-tracked receiver types one level deep*
(``x = SomeClass(...)`` at module level or locally,
``self.attr = SomeClass(...)`` inside methods).  Pass two resolves
call sites against those indexes:

* bare ``f(...)`` → same-file defs (any nesting level), then
  ``from M import f`` targets, then class constructors (edge to
  ``__init__``);
* ``alias.f(...)`` where ``alias`` imports a tree module → that
  module's ``f`` (or class ``f``'s ``__init__``);
* ``self.m(...)`` inside a method of class ``C`` → ``C.m`` (walking
  one level of resolvable bases) — **qualified**, no same-file
  homonym over-approximation;
* ``obj.m(...)`` / ``self.attr.m(...)`` where the receiver's type
  was assignment-tracked → that class's ``m``;
* anything else with an *unknown* receiver keeps the historical
  over-approximation (same-file homonym defs) — a false reachable
  edge costs a reviewed suppression, a false unreachable edge hides
  a real host sync.

**Taint.**  Every non-static parameter of a jit entry is
tracer-tainted at depth 0.  Taint propagates through local
assignments, call arguments (tainted arg → callee param, depth+1),
and return values (a callee that returns a tainted expression taints
the call result), bounded at ``KTPU_LINT_TAINT_DEPTH`` call edges
(default 3): a cast at depth 3 is a finding, the same cast at depth 4
is silence.  Static shape metadata (``.shape``/``.ndim``/``.dtype``/
``len()``) deliberately launders taint — those are Python ints under
trace.  Each tainted function carries a representative entry→here
call chain for the finding message.

Everything is memoized per :class:`Context` — resolution results per
function, taint summaries per (function, tainted-params) state — and
the per-file AST memo on :class:`SourceFile` keeps the whole build
single-traversal per file.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Context, SourceFile

FuncKey = Tuple[str, int]  # (file rel, def lineno)

#: taint propagation bound, in call edges from the jit entry
TAINT_DEPTH_DEFAULT = 3


def taint_depth() -> int:
    """Interprocedural taint bound (``KTPU_LINT_TAINT_DEPTH``)."""
    raw = os.environ.get('KTPU_LINT_TAINT_DEPTH', '')
    try:
        return int(raw) if raw else TAINT_DEPTH_DEFAULT
    except ValueError:
        return TAINT_DEPTH_DEFAULT


#: attribute reads that are static under trace — shape metadata is a
#: Python int/dtype, so taint does not flow through them
STATIC_ATTRS = {'shape', 'ndim', 'dtype', 'size', 'weak_type'}

#: builtins whose result is host-static even over a traced argument
STATIC_BUILTINS = {'len', 'isinstance', 'type', 'id', 'repr', 'str',
                   'hash', 'callable', 'getattr', 'hasattr', 'range'}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def walk_scope(fn: ast.AST):
    """Walk ``fn``'s subtree without descending into nested def/class
    scopes — nested functions are analyzed as their own (reachable)
    scopes, so walking them twice double-reports every finding."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


@dataclass
class ClassInfo:
    node: ast.ClassDef
    name: str
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    bases: List[ast.expr] = field(default_factory=list)
    #: ``self.<attr> = Ctor(...)`` sites: attr -> type token
    attr_types: Dict[str, Tuple] = field(default_factory=dict)
    #: first ``self.<attr> = <value>`` site per attr — the value node
    #: (KTPU201 checks these for mutable-container initializers)
    attr_values: Dict[str, ast.AST] = field(default_factory=dict)


@dataclass
class FuncInfo:
    node: ast.AST
    rel: str
    qualname: str            # module-dotted + lexical path
    cls: Optional[str]       # immediately enclosing class, if a method

    @property
    def key(self) -> FuncKey:
        return (self.rel, self.node.lineno)


@dataclass
class ModuleInfo:
    sf: SourceFile
    dotted: Optional[str]                      # dotted module name, if known
    defs: Dict[str, List[ast.AST]] = field(default_factory=dict)
    # local name -> ('module', dotted) | ('from', src, name)
    imports: Dict[str, Tuple] = field(default_factory=dict)
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    #: module-level ``NAME = Ctor(...)``: name -> type token
    var_types: Dict[str, Tuple] = field(default_factory=dict)
    #: every def in the file -> its FuncInfo
    func_info: Dict[ast.AST, FuncInfo] = field(default_factory=dict)


def _dotted_for(rel: str) -> Optional[str]:
    """Dotted module path for files that live in a package directory
    (``kyverno_tpu/ops/eval.py`` → ``kyverno_tpu.ops.eval``)."""
    if not rel.endswith('.py'):
        return None
    parts = rel[:-3].replace(os.sep, '/').split('/')
    if parts[-1] == '__init__':
        parts = parts[:-1]
    return '.'.join(parts) if parts else None


def _resolve_relative(dotted: Optional[str], level: int,
                      module: Optional[str]) -> Optional[str]:
    if level == 0:
        return module
    if dotted is None:
        return None
    base = dotted.split('.')
    # inside module X.Y.Z, `from . import` resolves against X.Y
    base = base[:-1]
    if level > 1:
        base = base[:-(level - 1)] if level - 1 <= len(base) else []
    if not base and module is None:
        return None
    return '.'.join(base + (module.split('.') if module else []))


def _type_token(ctor: ast.AST) -> Optional[Tuple]:
    """Type token for an ``x = Ctor(...)`` right-hand side: the
    constructor's spelling, resolved lazily against module indexes."""
    if not isinstance(ctor, ast.Call):
        return None
    f = ctor.func
    if isinstance(f, ast.Name):
        return ('local', f.id)
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return ('attr', f.value.id, f.attr)
    return None


class JitGraph:
    def __init__(self, ctx: Context):
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_dotted: Dict[str, ModuleInfo] = {}
        for sf in ctx.files:
            if sf.tree is None:
                continue
            mi = ModuleInfo(sf, _dotted_for(sf.rel))
            self._bind_module(mi)
            self.modules[sf.rel] = mi
            if mi.dotted:
                self.by_dotted[mi.dotted] = mi
        self._callee_cache: Dict[FuncKey, List[Tuple]] = {}
        self._local_type_cache: Dict[FuncKey, Dict[str, Tuple]] = {}
        self._return_taint_memo: Dict[Tuple[FuncKey, frozenset],
                                      bool] = {}
        self._tainted_locals_memo: Dict[Tuple[FuncKey, frozenset],
                                        Set[str]] = {}
        self._scope_cache: Dict[FuncKey, List[ast.AST]] = {}
        self.entries: List[Tuple[ModuleInfo, ast.AST, ast.AST]] = []
        self._find_entries()
        self.reachable: Set[FuncKey] = set()
        self._walk_reachable()
        #: merged tracer-tainted parameter names per function
        self.taint: Dict[FuncKey, Set[str]] = {}
        #: min call-edge distance from a jit entry, taint-bounded walk
        self.taint_min_depth: Dict[FuncKey, int] = {}
        #: representative entry→function qualname chain
        self.taint_chain: Dict[FuncKey, Tuple[str, ...]] = {}
        self._propagate_taint()

    # -- binder pass 1: per-module indexes -----------------------------------

    def _bind_module(self, mi: ModuleInfo) -> None:
        """Single recursive traversal building every per-module index:
        parents, defs, imports, classes/methods, receiver types."""
        mod_prefix = mi.dotted or mi.sf.rel

        def visit(node: ast.AST, qual: str, cls: Optional[ClassInfo],
                  in_func: bool) -> None:
            for child in ast.iter_child_nodes(node):
                mi.parents[child] = node
                if isinstance(child, _FUNC_DEFS):
                    mi.defs.setdefault(child.name, []).append(child)
                    qn = f'{qual}.{child.name}' if qual else child.name
                    mi.func_info[child] = FuncInfo(
                        child, mi.sf.rel, f'{mod_prefix}:{qn}',
                        cls.name if cls is not None else None)
                    if cls is not None:
                        cls.methods.setdefault(child.name, child)
                    visit(child, qn, None, True)
                elif isinstance(child, ast.ClassDef):
                    qn = f'{qual}.{child.name}' if qual else child.name
                    ci = ClassInfo(child, child.name,
                                   bases=list(child.bases))
                    # outermost same-name class wins; nested/shadowed
                    # definitions keep their own methods map
                    mi.classes.setdefault(child.name, ci)
                    visit(child, qn, ci, in_func)
                elif isinstance(child, ast.Import):
                    for alias in child.names:
                        mi.imports[alias.asname or
                                   alias.name.split('.')[0]] = \
                            ('module', alias.name)
                    visit(child, qual, cls, in_func)
                elif isinstance(child, ast.ImportFrom):
                    src = _resolve_relative(mi.dotted, child.level,
                                            child.module)
                    if src is not None:
                        for alias in child.names:
                            local = alias.asname or alias.name
                            mi.imports[local] = ('from', src, alias.name)
                    visit(child, qual, cls, in_func)
                elif isinstance(child, ast.Assign):
                    tok = _type_token(child.value)
                    for tgt in child.targets:
                        if isinstance(tgt, ast.Name) and not in_func:
                            if tok is not None:
                                mi.var_types.setdefault(tgt.id, tok)
                        elif isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == 'self' and \
                                cls is None and in_func:
                            # parent links exist up to `child`;
                            # resolve the owner from there
                            owner = self._owning_class(mi, child)
                            if owner is not None:
                                if tok is not None:
                                    owner.attr_types.setdefault(
                                        tgt.attr, tok)
                                owner.attr_values.setdefault(
                                    tgt.attr, child.value)
                    visit(child, qual, cls, in_func)
                else:
                    visit(child, qual, cls, in_func)

        visit(mi.sf.tree, '', None, False)

    def _owning_class(self, mi: ModuleInfo,
                      node: ast.AST) -> Optional[ClassInfo]:
        """The ClassInfo whose method lexically contains ``node``."""
        cur = mi.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                ci = mi.classes.get(cur.name)
                if ci is not None and ci.node is cur:
                    return ci
                return mi.classes.get(cur.name)
            cur = mi.parents.get(cur)
        return None

    # -- entry detection -----------------------------------------------------

    @staticmethod
    def is_jit_callable(func: ast.AST) -> bool:
        """``jax.jit`` / ``jit`` / ``pjit`` in call or decorator
        position (including ``partial(jax.jit, ...)``)."""
        if isinstance(func, ast.Name):
            return func.id in ('jit', 'pjit')
        if isinstance(func, ast.Attribute):
            return func.attr in ('jit', 'pjit')
        return False

    def _find_entries(self) -> None:
        for mi in self.modules.values():
            for node in mi.sf.walk():
                if isinstance(node, ast.Call) and \
                        self.is_jit_callable(node.func) and node.args:
                    target = node.args[0]
                    if isinstance(target, ast.Name):
                        for d in mi.defs.get(target.id, []):
                            self.entries.append((mi, d, node))
                    elif isinstance(target, ast.Attribute):
                        for tmi, d in self._resolve_attr_call(
                                mi, None, target):
                            self.entries.append((tmi, d, node))
                if isinstance(node, _FUNC_DEFS):
                    for dec in node.decorator_list:
                        call = dec if isinstance(dec, ast.Call) else None
                        if self.is_jit_callable(dec) or (
                                call is not None and (
                                    self.is_jit_callable(call.func) or
                                    any(self.is_jit_callable(a)
                                        for a in call.args))):
                            self.entries.append((mi, node, dec))

    @staticmethod
    def _static_entry_params(fn: ast.AST, site: ast.AST) -> Set[str]:
        """Param names pinned static at the jit site
        (``static_argnums`` / ``static_argnames``)."""
        out: Set[str] = set()
        if not isinstance(site, ast.Call):
            return out
        pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for kw in site.keywords:
            val = kw.value
            if kw.arg == 'static_argnums':
                nums = val.elts if isinstance(
                    val, (ast.Tuple, ast.List)) else [val]
                for n in nums:
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, int) and \
                            0 <= n.value < len(pos):
                        out.add(pos[n.value])
            elif kw.arg == 'static_argnames':
                names = val.elts if isinstance(
                    val, (ast.Tuple, ast.List)) else [val]
                for n in names:
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, str):
                        out.update(s.strip()
                                   for s in n.value.split(','))
        return out

    def entry_tainted_params(self, fn: ast.AST,
                             site: ast.AST) -> Set[str]:
        """The entry's tracer-tainted parameter names: every param
        except ``self``/``cls`` and the site's static args."""
        static = self._static_entry_params(fn, site)
        args = fn.args
        names = [a.arg for a in
                 args.posonlyargs + args.args + args.kwonlyargs]
        return {n for n in names
                if n not in static and n not in ('self', 'cls')}

    # -- binder pass 2: call resolution --------------------------------------

    def _resolve_class(self, mi: ModuleInfo, token: Tuple
                       ) -> Optional[Tuple[ModuleInfo, ClassInfo]]:
        """Resolve a type token to an in-tree class, chasing one
        import hop."""
        if token[0] == 'local':
            name = token[1]
            ci = mi.classes.get(name)
            if ci is not None:
                return mi, ci
            imp = mi.imports.get(name)
            if imp is not None and imp[0] == 'from':
                tgt = self.by_dotted.get(imp[1])
                if tgt is not None:
                    ci = tgt.classes.get(imp[2])
                    if ci is not None:
                        return tgt, ci
        elif token[0] == 'attr':
            imp = mi.imports.get(token[1])
            if imp is not None:
                dotted = imp[1] if imp[0] == 'module' \
                    else f'{imp[1]}.{imp[2]}'
                tgt = self.by_dotted.get(dotted)
                if tgt is not None:
                    ci = tgt.classes.get(token[2])
                    if ci is not None:
                        return tgt, ci
        return None

    def _class_method(self, mi: ModuleInfo, ci: ClassInfo, name: str,
                      _depth: int = 0
                      ) -> Optional[Tuple[ModuleInfo, ast.AST]]:
        """Look ``name`` up on ``ci``, then one level of resolvable
        base classes."""
        m = ci.methods.get(name)
        if m is not None:
            return mi, m
        if _depth >= 2:
            return None
        for base in ci.bases:
            tok = None
            if isinstance(base, ast.Name):
                tok = ('local', base.id)
            elif isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name):
                tok = ('attr', base.value.id, base.attr)
            if tok is None:
                continue
            resolved = self._resolve_class(mi, tok)
            if resolved is not None:
                hit = self._class_method(resolved[0], resolved[1],
                                         name, _depth + 1)
                if hit is not None:
                    return hit
        return None

    def _local_types(self, mi: ModuleInfo,
                     fn: Optional[ast.AST]) -> Dict[str, Tuple]:
        """``x = Ctor(...)`` receiver types local to ``fn`` (one
        assignment hop, memoized)."""
        if fn is None:
            return {}
        key = (mi.sf.rel, fn.lineno)
        hit = self._local_type_cache.get(key)
        if hit is not None:
            return hit
        out: Dict[str, Tuple] = {}
        for node in self.scope_nodes(mi, fn):
            if isinstance(node, ast.Assign):
                tok = _type_token(node.value)
                if tok is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.setdefault(tgt.id, tok)
        self._local_type_cache[key] = out
        return out

    def _receiver_token(self, mi: ModuleInfo, fn: Optional[ast.AST],
                        base: ast.AST) -> Optional[Tuple]:
        """Type token of a call receiver expression, if tracked."""
        if isinstance(base, ast.Name):
            tok = self._local_types(mi, fn).get(base.id)
            if tok is not None:
                return tok
            return mi.var_types.get(base.id)
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == 'self' and fn is not None:
            info = mi.func_info.get(fn)
            if info is not None and info.cls is not None:
                ci = mi.classes.get(info.cls)
                if ci is not None:
                    return ci.attr_types.get(base.attr)
        return None

    def _resolve_attr_call(self, mi: ModuleInfo, fn: Optional[ast.AST],
                           f: ast.Attribute
                           ) -> List[Tuple[ModuleInfo, ast.AST]]:
        base = f.value
        # self.m() → the enclosing class's method, qualified
        if isinstance(base, ast.Name) and base.id == 'self' and \
                fn is not None:
            info = mi.func_info.get(fn)
            if info is not None and info.cls is not None:
                ci = mi.classes.get(info.cls)
                if ci is not None:
                    hit = self._class_method(mi, ci, f.attr)
                    if hit is not None:
                        return [hit]
                    tok = ci.attr_types.get(f.attr)
                    if tok is not None:
                        # self.attr holds a tracked instance and is
                        # being *called*: jit(self.fn)-style callables
                        resolved = self._resolve_class(mi, tok)
                        if resolved is not None:
                            hit = self._class_method(
                                resolved[0], resolved[1], '__call__')
                            if hit is not None:
                                return [hit]
                    return []  # per-class lookup is authoritative
        # typed receiver (local/module var, self.attr) → that class
        tok = self._receiver_token(mi, fn, base)
        if tok is not None:
            resolved = self._resolve_class(mi, tok)
            if resolved is not None:
                hit = self._class_method(resolved[0], resolved[1],
                                         f.attr)
                return [hit] if hit is not None else []
        # alias.f() → the imported module's f (def or class ctor)
        if isinstance(base, ast.Name):
            imp = mi.imports.get(base.id)
            if imp is not None:
                dotted = imp[1] if imp[0] == 'module' \
                    else f'{imp[1]}.{imp[2]}'
                tgt = self.by_dotted.get(dotted)
                if tgt is not None:
                    out = [(tgt, d) for d in tgt.defs.get(f.attr, [])
                           if self._is_top_level(tgt, d)]
                    ci = tgt.classes.get(f.attr)
                    if ci is not None and '__init__' in ci.methods:
                        out.append((tgt, ci.methods['__init__']))
                    if out or imp[0] == 'module':
                        return out
        # unknown receiver: historical same-file homonym fallback
        return [(mi, d) for d in mi.defs.get(f.attr, [])]

    @staticmethod
    def _is_top_level(mi: ModuleInfo, d: ast.AST) -> bool:
        return isinstance(mi.parents.get(d), ast.Module)

    def resolve_call(self, mi: ModuleInfo, fn: Optional[ast.AST],
                     call: ast.Call
                     ) -> List[Tuple[ModuleInfo, ast.AST]]:
        """Resolve one call site to its in-tree target def(s)."""
        f = call.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in mi.defs:
                return [(mi, d) for d in mi.defs[name]]
            ci = mi.classes.get(name)
            if ci is not None:
                init = ci.methods.get('__init__')
                return [(mi, init)] if init is not None else []
            imp = mi.imports.get(name)
            if imp is not None and imp[0] == 'from':
                tgt = self.by_dotted.get(imp[1])
                if tgt is not None:
                    out = [(tgt, d) for d in tgt.defs.get(imp[2], [])
                           if self._is_top_level(tgt, d)]
                    ci = tgt.classes.get(imp[2])
                    if ci is not None and '__init__' in ci.methods:
                        out.append((tgt, ci.methods['__init__']))
                    return out
            return []
        if isinstance(f, ast.Attribute):
            return self._resolve_attr_call(mi, fn, f)
        return []

    def callees(self, mi: ModuleInfo, fn: ast.AST
                ) -> List[Tuple[ModuleInfo, ast.AST, ast.Call]]:
        """Every resolved call edge out of ``fn`` (memoized).  Walks
        the full subtree including nested defs — a closure's calls run
        when the closure does, and the closure is only reachable via
        its enclosing function."""
        key = (mi.sf.rel, fn.lineno)
        hit = self._callee_cache.get(key)
        if hit is not None:
            return hit
        out: List[Tuple[ModuleInfo, ast.AST, ast.Call]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                for tmi, d in self.resolve_call(mi, fn, node):
                    out.append((tmi, d, node))
        self._callee_cache[key] = out
        return out

    # -- reachability --------------------------------------------------------

    def _walk_reachable(self) -> None:
        work: List[Tuple[ModuleInfo, ast.AST]] = \
            [(mi, fn) for mi, fn, _site in self.entries]
        while work:
            mi, fn = work.pop()
            key = (mi.sf.rel, fn.lineno)
            if key in self.reachable:
                continue
            self.reachable.add(key)
            work.extend((tmi, d) for tmi, d, _c in self.callees(mi, fn))

    def reachable_set(self, mi: ModuleInfo,
                      fn: ast.AST) -> Set[FuncKey]:
        """Transitive closure of call edges from ``fn`` (inclusive) —
        the reachability primitive the KTPU6xx thread passes reuse."""
        seen: Set[FuncKey] = set()
        work = [(mi, fn)]
        while work:
            cmi, cfn = work.pop()
            key = (cmi.sf.rel, cfn.lineno)
            if key in seen:
                continue
            seen.add(key)
            work.extend((tmi, d)
                        for tmi, d, _c in self.callees(cmi, cfn))
        return seen

    # -- taint lattice -------------------------------------------------------

    def _bind_args(self, callee: ast.AST, call: ast.Call,
                   is_method_call: bool) -> List[Tuple[str, ast.AST]]:
        """(param name, arg expr) pairs for a resolved call."""
        args = callee.args
        pos = [a.arg for a in args.posonlyargs + args.args]
        if is_method_call and pos and pos[0] in ('self', 'cls'):
            pos = pos[1:]
        out: List[Tuple[str, ast.AST]] = []
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                break
            if i < len(pos):
                out.append((pos[i], a))
        kw_ok = {a.arg for a in
                 args.posonlyargs + args.args + args.kwonlyargs}
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in kw_ok:
                out.append((kw.arg, kw.value))
        return out

    def expr_tainted(self, mi: ModuleInfo, fn: Optional[ast.AST],
                     expr: ast.AST, tainted: Set[str],
                     _depth: int = 0) -> bool:
        """Does ``expr`` (under ``tainted`` names) carry a tracer?
        Shape metadata and host-static builtins launder taint; calls
        consult the callee's return-taint summary."""
        if _depth > 6:
            return False
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(mi, fn, expr.value, tainted,
                                     _depth + 1)
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Name) and f.id in STATIC_BUILTINS:
                return False
            root = f
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and \
                    root.id in ('jnp', 'jax', 'lax'):
                return True
            targets = self.resolve_call(mi, fn, expr) \
                if fn is not None else []
            if targets:
                for tmi, d in targets:
                    bound = self._bind_args(
                        d, expr, isinstance(f, ast.Attribute))
                    sub = {p for p, a in bound
                           if self.expr_tainted(mi, fn, a, tainted,
                                                _depth + 1)}
                    if sub and self.returns_tainted(tmi, d,
                                                    frozenset(sub)):
                        return True
                # a method *on* a tainted receiver stays tainted even
                # when the callee body is opaque (t.sum(), t.astype())
            if isinstance(f, ast.Attribute) and not targets and \
                    self.expr_tainted(mi, fn, f.value, tainted,
                                      _depth + 1):
                return True
            return any(self.expr_tainted(mi, fn, a, tainted, _depth + 1)
                       for a in expr.args) and not targets
        return any(self.expr_tainted(mi, fn, c, tainted, _depth + 1)
                   for c in ast.iter_child_nodes(expr))

    def tainted_locals(self, mi: ModuleInfo, fn: ast.AST,
                       params: Set[str]) -> Set[str]:
        """Tainted names visible in ``fn``: the tainted params plus
        locals assigned (transitively, to a small fixpoint) from
        tainted expressions."""
        memo_key = ((mi.sf.rel, fn.lineno), frozenset(params))
        hit = self._tainted_locals_memo.get(memo_key)
        if hit is not None:
            return set(hit)
        tainted = set(params)
        assigns = [n for n in self.scope_nodes(mi, fn)
                   if isinstance(n, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign))]
        for _round in range(3):
            grew = False
            for node in assigns:
                value = node.value
                if value is None or not self.expr_tainted(
                        mi, fn, value, tainted):
                    continue
                targets = node.targets \
                    if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    elts = t.elts if isinstance(
                        t, (ast.Tuple, ast.List)) else [t]
                    for e in elts:
                        if isinstance(e, ast.Name) and \
                                e.id not in tainted:
                            tainted.add(e.id)
                            grew = True
            if not grew:
                break
        self._tainted_locals_memo[memo_key] = set(tainted)
        return tainted

    def returns_tainted(self, mi: ModuleInfo, fn: ast.AST,
                        params: frozenset) -> bool:
        """Does ``fn`` return a tainted value when ``params`` are
        tainted?  Memoized; cycles assume False (under-approximate —
        a missed return edge costs a missed finding, never a false
        one)."""
        key = ((mi.sf.rel, fn.lineno), params)
        hit = self._return_taint_memo.get(key)
        if hit is not None:
            return hit
        self._return_taint_memo[key] = False  # cycle guard
        tainted = self.tainted_locals(mi, fn, set(params))
        result = False
        for node in self.scope_nodes(mi, fn):
            if isinstance(node, ast.Return) and node.value is not None:
                if self.expr_tainted(mi, fn, node.value, tainted):
                    result = True
                    break
        self._return_taint_memo[key] = result
        return result

    def _propagate_taint(self) -> None:
        limit = taint_depth()
        work: List[FuncKey] = []
        infos: Dict[FuncKey, Tuple[ModuleInfo, ast.AST]] = {}
        for mi, fn, site in self.entries:
            key = (mi.sf.rel, fn.lineno)
            params = self.entry_tainted_params(fn, site)
            if not params:
                continue
            infos[key] = (mi, fn)
            prev = self.taint.setdefault(key, set())
            if not params <= prev or key not in self.taint_min_depth:
                prev.update(params)
                self.taint_min_depth[key] = 0
                info = mi.func_info.get(fn)
                self.taint_chain.setdefault(
                    key, (info.qualname if info else fn.name,))
                work.append(key)
        while work:
            key = work.pop()
            mi, fn = infos[key]
            depth = self.taint_min_depth[key]
            if depth >= limit:
                continue
            params = set(self.taint[key])
            tainted = self.tainted_locals(mi, fn, params)
            for tmi, d, call in self.callees(mi, fn):
                bound = self._bind_args(
                    d, call, isinstance(call.func, ast.Attribute))
                sub = {p for p, a in bound
                       if self.expr_tainted(mi, fn, a, tainted)}
                if not sub:
                    continue
                tkey = (tmi.sf.rel, d.lineno)
                prev = self.taint.setdefault(tkey, set())
                old_depth = self.taint_min_depth.get(tkey)
                new_depth = depth + 1
                changed = not sub <= prev
                prev.update(sub)
                if old_depth is None or new_depth < old_depth:
                    self.taint_min_depth[tkey] = new_depth
                    changed = True
                if tkey not in self.taint_chain:
                    info = tmi.func_info.get(d)
                    self.taint_chain[tkey] = self.taint_chain[key] + \
                        (info.qualname if info else d.name,)
                if changed:
                    infos[tkey] = (tmi, d)
                    work.append(tkey)

    def tainted_names_for(self, mi: ModuleInfo,
                          fn: ast.AST) -> Set[str]:
        """Tainted params ∪ tainted locals for a reachable function
        (empty when taint never reaches it)."""
        params = self.taint.get((mi.sf.rel, fn.lineno))
        if not params:
            return set()
        return self.tainted_locals(mi, fn, params)

    def chain_for(self, mi: ModuleInfo, fn: ast.AST) -> str:
        """Rendered entry→function call chain for finding messages."""
        chain = self.taint_chain.get((mi.sf.rel, fn.lineno))
        if not chain:
            return ''
        return ' -> '.join(chain)

    # -- queries -------------------------------------------------------------

    def scope_nodes(self, mi: ModuleInfo,
                    fn: ast.AST) -> List[ast.AST]:
        """Memoized :func:`walk_scope` — every pass asking for a
        function's own-scope nodes shares one traversal."""
        key = (mi.sf.rel, fn.lineno)
        hit = self._scope_cache.get(key)
        if hit is None:
            hit = list(walk_scope(fn))
            self._scope_cache[key] = hit
        return hit

    def reachable_functions(self):
        """Yield ``(SourceFile, ModuleInfo, FunctionDef)`` for every
        function whose body may execute under a jit trace."""
        for mi in self.modules.values():
            for defs in mi.defs.values():
                for d in defs:
                    if (mi.sf.rel, d.lineno) in self.reachable:
                        yield mi.sf, mi, d

    def enclosing_scopes(self, mi: ModuleInfo, fn: ast.AST) -> List[ast.AST]:
        """Lexically enclosing function scopes (innermost first), then
        the module."""
        out: List[ast.AST] = []
        node = mi.parents.get(fn)
        while node is not None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Module)):
                out.append(node)
            node = mi.parents.get(node)
        return out

    def function_by_name(self, name: str
                         ) -> List[Tuple[ModuleInfo, ast.AST]]:
        """Every def matching ``name`` — bare (``_worker``), qualified
        (``ChunkPipeline._worker``), or dotted-module-prefixed
        (``kyverno_tpu.compiler.pipeline:ChunkPipeline._worker``) —
        for ``--graph-dump``."""
        out: List[Tuple[ModuleInfo, ast.AST]] = []
        for mi in self.modules.values():
            for info in mi.func_info.values():
                qn = info.qualname
                short = qn.split(':', 1)[1] if ':' in qn else qn
                if name in (qn, short, short.split('.')[-1]):
                    out.append((mi, info.node))
        return out

    def graph_dump(self, mi: ModuleInfo, fn: ast.AST) -> dict:
        """Resolved callees + taint facts for one function (the
        ``--graph-dump`` payload)."""
        info = mi.func_info.get(fn)
        key = (mi.sf.rel, fn.lineno)
        callees = []
        seen = set()
        for tmi, d, call in self.callees(mi, fn):
            tinfo = tmi.func_info.get(d)
            ck = (tmi.sf.rel, d.lineno, call.lineno)
            if ck in seen:
                continue
            seen.add(ck)
            callees.append({
                'qualname': tinfo.qualname if tinfo else d.name,
                'file': tmi.sf.rel, 'line': d.lineno,
                'call_line': call.lineno,
                'jit_reachable': (tmi.sf.rel, d.lineno)
                                 in self.reachable})
        return {
            'qualname': info.qualname if info else fn.name,
            'file': mi.sf.rel, 'line': fn.lineno,
            'class': info.cls if info else None,
            'jit_reachable': key in self.reachable,
            'callees': callees,
            'taint': {
                'params': sorted(self.taint.get(key, ())),
                'depth': self.taint_min_depth.get(key),
                'chain': list(self.taint_chain.get(key, ())),
                'names': sorted(self.tainted_names_for(mi, fn)),
            },
        }


def jit_graph(ctx: Context) -> JitGraph:
    return ctx.cached('jitgraph', lambda: JitGraph(ctx))
