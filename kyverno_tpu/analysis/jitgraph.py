"""Jit-entry call graph: which functions can run under a trace.

The trace-safety (KTPU1xx) and retrace (KTPU2xx) passes share one
over-approximated reachability question: *could this function's body
execute inside ``jax.jit``?*  Entry points are functions passed to
``jax.jit`` / ``pjit`` (call form) or decorated with them; edges are
resolved statically:

* bare-name calls → defs in the same file (any nesting level);
* ``from M import f`` calls → ``f``'s top-level def in ``M`` when ``M``
  is part of the analyzed tree (relative imports resolved against the
  importing module's package, function-level imports included);
* ``alias.f(...)`` calls where ``alias`` imports a tree module → that
  module's ``f``;
* ``obj.method(...)`` calls → same-file defs named ``method`` when the
  name is unambiguous there (covers ``self.x`` and helper-class
  methods without pretending to do type inference).

This deliberately over-approximates (a shared method name pulls in
every same-file homonym) — for lint purposes a false reachable edge
costs a reviewed suppression, a false unreachable edge hides a real
host sync.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import Context, SourceFile

FuncKey = Tuple[str, int]  # (file rel, def lineno)


def walk_scope(fn: ast.AST):
    """Walk ``fn``'s subtree without descending into nested def/class
    scopes — nested functions are analyzed as their own (reachable)
    scopes, so walking them twice double-reports every finding."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


@dataclass
class ModuleInfo:
    sf: SourceFile
    dotted: Optional[str]                      # dotted module name, if known
    defs: Dict[str, List[ast.AST]] = field(default_factory=dict)
    # local name -> ('module', dotted) | ('func', dotted, name)
    imports: Dict[str, Tuple] = field(default_factory=dict)
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)


def _dotted_for(rel: str) -> Optional[str]:
    """Dotted module path for files that live in a package directory
    (``kyverno_tpu/ops/eval.py`` → ``kyverno_tpu.ops.eval``)."""
    if not rel.endswith('.py'):
        return None
    parts = rel[:-3].replace(os.sep, '/').split('/')
    if parts[-1] == '__init__':
        parts = parts[:-1]
    return '.'.join(parts) if parts else None


def _resolve_relative(dotted: Optional[str], level: int,
                      module: Optional[str]) -> Optional[str]:
    if level == 0:
        return module
    if dotted is None:
        return None
    base = dotted.split('.')
    # inside module X.Y.Z, `from . import` resolves against X.Y
    base = base[:-1]
    if level > 1:
        base = base[:-(level - 1)] if level - 1 <= len(base) else []
    if not base and module is None:
        return None
    return '.'.join(base + (module.split('.') if module else []))


class JitGraph:
    def __init__(self, ctx: Context):
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_dotted: Dict[str, ModuleInfo] = {}
        for sf in ctx.files:
            if sf.tree is None:
                continue
            mi = ModuleInfo(sf, _dotted_for(sf.rel))
            for node in ast.walk(sf.tree):
                for child in ast.iter_child_nodes(node):
                    mi.parents[child] = node
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    mi.defs.setdefault(node.name, []).append(node)
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        mi.imports[alias.asname or
                                   alias.name.split('.')[0]] = \
                            ('module', alias.name)
                elif isinstance(node, ast.ImportFrom):
                    src = _resolve_relative(mi.dotted, node.level,
                                            node.module)
                    if src is None:
                        continue
                    for alias in node.names:
                        local = alias.asname or alias.name
                        mi.imports[local] = ('from', src, alias.name)
            self.modules[sf.rel] = mi
            if mi.dotted:
                self.by_dotted[mi.dotted] = mi
        self.entries: List[Tuple[ModuleInfo, ast.AST, ast.AST]] = []
        self._find_entries()
        self.reachable: Set[FuncKey] = set()
        self._walk_reachable()

    # -- entry detection -----------------------------------------------------

    @staticmethod
    def is_jit_callable(func: ast.AST) -> bool:
        """``jax.jit`` / ``jit`` / ``pjit`` in call or decorator
        position (including ``partial(jax.jit, ...)``)."""
        if isinstance(func, ast.Name):
            return func.id in ('jit', 'pjit')
        if isinstance(func, ast.Attribute):
            return func.attr in ('jit', 'pjit')
        return False

    def _find_entries(self) -> None:
        for mi in self.modules.values():
            tree = mi.sf.tree
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) and \
                        self.is_jit_callable(node.func) and node.args:
                    target = node.args[0]
                    if isinstance(target, ast.Name):
                        for d in mi.defs.get(target.id, []):
                            self.entries.append((mi, d, node))
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        call = dec if isinstance(dec, ast.Call) else None
                        if self.is_jit_callable(dec) or (
                                call is not None and (
                                    self.is_jit_callable(call.func) or
                                    any(self.is_jit_callable(a)
                                        for a in call.args))):
                            self.entries.append((mi, node, dec))

    # -- reachability --------------------------------------------------------

    def _callees(self, mi: ModuleInfo, fn: ast.AST
                 ) -> List[Tuple[ModuleInfo, ast.AST]]:
        out: List[Tuple[ModuleInfo, ast.AST]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                name = f.id
                if name in mi.defs:
                    out.extend((mi, d) for d in mi.defs[name])
                    continue
                imp = mi.imports.get(name)
                if imp and imp[0] == 'from':
                    tgt = self.by_dotted.get(imp[1])
                    if tgt is not None:
                        out.extend((tgt, d)
                                   for d in tgt.defs.get(imp[2], []))
            elif isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name):
                    imp = mi.imports.get(base.id)
                    if imp is not None:
                        if imp[0] == 'module':
                            tgt = self.by_dotted.get(imp[1])
                        else:
                            tgt = self.by_dotted.get(f'{imp[1]}.{imp[2]}')
                        if tgt is not None:
                            out.extend((tgt, d)
                                       for d in tgt.defs.get(f.attr, []))
                            continue
                # unqualified method call: same-file defs by attr name
                out.extend((mi, d) for d in mi.defs.get(f.attr, []))
        return out

    def _walk_reachable(self) -> None:
        work: List[Tuple[ModuleInfo, ast.AST]] = \
            [(mi, fn) for mi, fn, _site in self.entries]
        while work:
            mi, fn = work.pop()
            key = (mi.sf.rel, fn.lineno)
            if key in self.reachable:
                continue
            self.reachable.add(key)
            work.extend(self._callees(mi, fn))

    # -- queries -------------------------------------------------------------

    def reachable_functions(self):
        """Yield ``(SourceFile, FunctionDef)`` for every function whose
        body may execute under a jit trace."""
        for mi in self.modules.values():
            for defs in mi.defs.values():
                for d in defs:
                    if (mi.sf.rel, d.lineno) in self.reachable:
                        yield mi.sf, mi, d

    def enclosing_scopes(self, mi: ModuleInfo, fn: ast.AST) -> List[ast.AST]:
        """Lexically enclosing function scopes (innermost first), then
        the module."""
        out: List[ast.AST] = []
        node = mi.parents.get(fn)
        while node is not None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Module)):
                out.append(node)
            node = mi.parents.get(node)
        return out


def jit_graph(ctx: Context) -> JitGraph:
    return ctx.cached('jitgraph', lambda: JitGraph(ctx))
