"""Metric- and span-catalog passes (KTPU5xx) — the framework home of
what ``scripts/check_metric_names.py`` used to do standalone (the
script is now a thin shim over this module; its allowlist semantics,
module API, and exit codes are unchanged).

* **KTPU501** — a registry write (``inc`` / ``observe`` / ``set_gauge``
  / ``clear_gauge`` / ``register_histogram``) uses a metric name absent
  from ``observability/catalog.py``.
* **KTPU502** — a write site whose name argument is neither a string
  literal nor a resolvable UPPER_CASE module constant (uncheckable —
  use a constant).
* **KTPU503** — dead metric: a cataloged name with no write site in
  the tree (``DEAD_METRIC_ALLOWLIST`` names the deliberate
  exceptions, each with the reason it may exist without an emitter).
  The allowlist is itself checked both ways: an entry whose metric
  *gained* a write site is stale (the exception no longer excuses
  anything — remove it so the metric is catalog-checked like every
  other), and an entry naming a metric absent from the catalog is
  dead weight.  New subsystems therefore can't hide behind the
  allowlist: the moment their emitter lands, only the catalog rules.
* **KTPU504** — a span start site (``start_span`` / a device
  ``stage(...)`` timer) whose name is absent from the span catalog
  (``observability/catalog.py:SPANS``), or whose name cannot be
  resolved at all.  Dynamic (f-string) names are checked by literal
  prefix against the catalog, so route-templated spans like
  ``webhooks/<route>`` stay checkable.
* **KTPU505** — dead span: a cataloged span name nothing in the tree
  starts — the span analogue of KTPU503, so the README span table
  (generated from the same catalog) can never document spans that no
  longer exist.
* **KTPU507** — pipeline stage-label drift: a ``stage('<s>')`` /
  ``exec_scope`` / ``ChunkPipeline`` stage-list / ``add_backpressure``
  label used under ``compiler/`` that is not registered in
  ``observability/catalog.py:PIPELINE_STAGES`` (the timeline
  critical-path walk and the blame metric group by registered names,
  so an unregistered label silently drops out of attribution), or a
  registered stage with no use site anywhere in the tree (dead-stage
  check, the KTPU503/505 analogue).
* **KTPU508** — partition key hygiene: an ``executable_cache_key``
  call site outside ``kyverno_tpu/partition/`` whose fingerprint
  operand (resolved one level through enclosing-scope bindings, the
  KTPU204 depth) consumes ``policy_set_fingerprint`` — the whole-set
  fingerprint in a compile/AOT key means one policy edit invalidates
  every partition's executables; draw it from
  ``partition/keys.compile_fingerprint`` instead.
* **KTPU509** — fleet-scope hygiene: metrics written from the mesh
  path (``kyverno_tpu/parallel/``) feed the cross-host federation
  (``observability/fleet.py``), so without a shard/host identity label
  the merged view collapses every process's series into one lying
  number.  The catalog's ``fleet_scope`` field names the required
  label key (``shard`` / ``mesh``); the pass flags a parallel/ write
  of a metric with no declared scope, any write of a scoped metric
  missing its identity keyword, and a declared scope no parallel/
  write site exercises (dead scope, the KTPU503/505 analogue).
* **KTPU506** — unit mismatch at a write site: a cataloged metric whose
  name declares its unit (``*_seconds[_total]`` / ``*_bytes[_total]``)
  is fed a value that carries the wrong one — a ``*_ms`` name with no
  ``/ 1000`` conversion in the expression (milliseconds exported as
  seconds are off by 1000x on every dashboard), or ``len()`` of a str
  for a bytes metric (characters, not bytes — encode first).  Values
  are resolved one level through local assignments, the same
  local-dataflow depth as KTPU204.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, Iterable, List, Optional, Tuple

from .core import Context, Finding, SourceFile, register

WRITE_METHODS = {'inc', 'observe', 'set_gauge', 'clear_gauge',
                 'register_histogram'}

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PACKAGE = os.path.join(REPO_ROOT, 'kyverno_tpu')
CATALOG_PATH = os.path.join(PACKAGE, 'observability', 'catalog.py')

#: catalog entries with no write site in the tree that are legitimately
#: alive — the ONLY names the dead-metric pass may skip, each with the
#: reason it is allowed to exist without an emitter
DEAD_METRIC_ALLOWLIST = {
    'kyverno_client_queries_total':
        'reserved for a real cluster client transport (dclient '
        'interface exists; the in-memory fake does not emit queries)',
    'kyverno_tpu_metric_series_dropped_total':
        'written by the registry cardinality guard itself '
        '(metrics.py:_admit) through direct series access — an inc() '
        'there would recurse into the guard',
}


def _module_constants(tree: ast.Module) -> Dict[str, str]:
    """UPPER_CASE module-level string assignments (metric name consts)."""
    consts: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id.isupper():
                    consts[target.id] = node.value.value
    return consts


def _consts(sf: SourceFile) -> Dict[str, str]:
    """Per-file memo of ``_module_constants`` — several collectors and
    passes re-read the same files, and the constant map never changes
    within a run."""
    cached = getattr(sf, '_catalog_consts', None)
    if cached is None:
        cached = _module_constants(sf.tree)
        sf._catalog_consts = cached
    return cached


def _write_sites(ctx: Context):
    return ctx.cached('catalog:writes',
                      lambda: collect_from_files(ctx.files))


def _span_sites(ctx: Context):
    return ctx.cached('catalog:spans',
                      lambda: collect_span_sites(ctx.files))


def collect_from_files(files: List[SourceFile]
                       ) -> Tuple[List[Tuple[SourceFile, int, str]],
                                  List[Tuple[SourceFile, int, str]]]:
    """(resolved [(file, line, metric_name)], unresolved
    [(file, line, description)]) across a parsed file set."""
    all_consts: Dict[str, str] = {}
    for sf in files:
        if sf.tree is not None:
            all_consts.update(_consts(sf))
    resolved: List[Tuple[SourceFile, int, str]] = []
    unresolved: List[Tuple[SourceFile, int, str]] = []
    for sf in files:
        if sf.tree is None:
            continue
        local_consts = _consts(sf)
        for node in sf.walk():
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in WRITE_METHODS and node.args):
                continue
            arg = node.args[0]
            name: Optional[str] = None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
            elif isinstance(arg, ast.Name):
                name = local_consts.get(arg.id, all_consts.get(arg.id))
            elif isinstance(arg, ast.Attribute):
                # module.CONST spelling: resolve by attribute name
                name = all_consts.get(arg.attr)
            if name is None:
                unresolved.append((sf, node.lineno, ast.dump(arg)[:80]))
            else:
                resolved.append((sf, node.lineno, name))
    return resolved, unresolved


def load_catalog() -> Dict[str, Tuple[str, str]]:
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from kyverno_tpu.observability.catalog import METRICS
    return {name: (m.type, m.help) for name, m in METRICS.items()}


@register('KTPU501', 'metric write site with a name missing from '
                     'observability/catalog.py')
def _check_uncataloged(ctx: Context) -> Iterable[Finding]:
    catalog = load_catalog()
    resolved, _unresolved = _write_sites(ctx)
    for sf, line, name in resolved:
        if name not in catalog:
            yield sf.finding(
                'KTPU501', line,
                f'metric {name!r} is not in observability/catalog.py '
                f'— catalog it with a type and help text')


@register('KTPU502', 'metric write site whose name is not a literal '
                     'or module constant (uncheckable)')
def _check_unresolved(ctx: Context) -> Iterable[Finding]:
    _resolved, unresolved = _write_sites(ctx)
    for sf, line, desc in unresolved:
        yield sf.finding(
            'KTPU502', line,
            f'metric name is not a literal or module constant '
            f'({desc}) — uncheckable, use a constant')


def stale_allowlist_entries(catalog, used) -> List[Tuple[str, str]]:
    """(name, problem) per DEAD_METRIC_ALLOWLIST entry that no longer
    excuses anything: the metric gained a write site (the common case
    when a reserved metric's subsystem finally lands) or fell out of
    the catalog entirely."""
    out: List[Tuple[str, str]] = []
    for name in sorted(DEAD_METRIC_ALLOWLIST):
        if name not in catalog:
            out.append((name, 'names a metric absent from the catalog'))
        elif name in used:
            out.append((name, 'has a write site now — the metric is '
                              'catalog-checked like any other'))
    return out


@register('KTPU503', 'dead metric: cataloged name with no write site '
                     'in the tree (or stale allowlist entry)')
def _check_dead_metrics(ctx: Context) -> Iterable[Finding]:
    catalog = load_catalog()
    resolved, _unresolved = _write_sites(ctx)
    used = {name for _sf, _l, name in resolved}
    anchor = ctx.by_rel('kyverno_tpu/observability/catalog.py')

    def locate(name):
        target = anchor if anchor is not None else ctx.files[0]
        line = 1
        if anchor is not None:
            for i, text in enumerate(anchor.lines, start=1):
                if f"'{name}'" in text:
                    line = i
                    break
        return target, line

    for name in sorted(catalog):
        if name in used or name in DEAD_METRIC_ALLOWLIST:
            continue
        target, line = locate(name)
        yield target.finding(
            'KTPU503', line,
            f'catalog: {name} has no write site in the tree — remove '
            f'the entry, add the emitter, or allowlist it with a '
            f'reason (DEAD_METRIC_ALLOWLIST)')
    for name, problem in stale_allowlist_entries(catalog, used):
        target, line = locate(name)
        yield target.finding(
            'KTPU503', line,
            f'DEAD_METRIC_ALLOWLIST: {name} {problem} — drop the '
            f'stale allowlist entry')


# -- span catalog (KTPU504/505) ----------------------------------------------

def load_span_catalog() -> Dict[str, str]:
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from kyverno_tpu.observability.catalog import SPANS
    return dict(SPANS)


def _fstring_prefix(node: ast.JoinedStr) -> str:
    """Leading literal text of an f-string span name — the checkable
    part of a templated name like ``f'webhooks{path}'``."""
    prefix = ''
    for part in node.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            prefix += part.value
        else:
            break
    return prefix


def collect_span_sites(files: List[SourceFile]
                       ) -> Tuple[List[Tuple[SourceFile, int, str]],
                                  List[Tuple[SourceFile, int, str]],
                                  List[Tuple[SourceFile, int, str]]]:
    """Span start sites across a parsed file set: (exact
    [(file, line, name)], dynamic [(file, line, prefix)], unresolved
    [(file, line, description)]).

    ``start_span(<name>)`` sites contribute the name directly; device
    ``stage('<s>')`` timers contribute ``kyverno/device/<s>`` (the
    generic ``f'kyverno/device/{name}'`` start inside ``stage`` itself
    lands in the dynamic set)."""
    all_consts: Dict[str, str] = {}
    for sf in files:
        if sf.tree is not None:
            all_consts.update(_consts(sf))
    exact: List[Tuple[SourceFile, int, str]] = []
    dynamic: List[Tuple[SourceFile, int, str]] = []
    unresolved: List[Tuple[SourceFile, int, str]] = []
    for sf in files:
        if sf.tree is None:
            continue
        local_consts = _consts(sf)
        for node in sf.walk():
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) else \
                (func.id if isinstance(func, ast.Name) else '')
            if attr not in ('start_span', 'stage'):
                continue
            arg = node.args[0]
            name: Optional[str] = None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
            elif isinstance(arg, ast.Name):
                name = local_consts.get(arg.id, all_consts.get(arg.id))
            elif isinstance(arg, ast.JoinedStr):
                prefix = _fstring_prefix(arg)
                if attr == 'stage':
                    prefix = 'kyverno/device/' + prefix
                dynamic.append((sf, node.lineno, prefix))
                continue
            if name is None:
                # a `stage` param (def stage(name...)) has no literal —
                # only calls matter, and non-constant args through a
                # variable are uncheckable
                unresolved.append((sf, node.lineno, ast.dump(arg)[:80]))
                continue
            if attr == 'stage':
                name = 'kyverno/device/' + name
            exact.append((sf, node.lineno, name))
    return exact, dynamic, unresolved


@register('KTPU504', 'span start site with a name missing from the '
                     'span catalog (observability/catalog.py SPANS) '
                     'or unresolvable')
def _check_uncataloged_spans(ctx: Context) -> Iterable[Finding]:
    catalog = load_span_catalog()
    exact, dynamic, unresolved = _span_sites(ctx)
    for sf, line, name in exact:
        if name not in catalog:
            yield sf.finding(
                'KTPU504', line,
                f'span {name!r} is not in the span catalog '
                f'(observability/catalog.py SPANS) — catalog it with '
                f'help text')
    for sf, line, prefix in dynamic:
        if not prefix or not any(s.startswith(prefix) for s in catalog):
            yield sf.finding(
                'KTPU504', line,
                f'dynamic span name with prefix {prefix!r} matches no '
                f'span catalog entry — catalog a templated name '
                f'(e.g. "{prefix}<...>")')
    for sf, line, desc in unresolved:
        yield sf.finding(
            'KTPU504', line,
            f'span name is not a literal, module constant, or '
            f'f-string ({desc}) — uncheckable, use a constant')


@register('KTPU505', 'dead span: cataloged span name with no start '
                     'site in the tree')
def _check_dead_spans(ctx: Context) -> Iterable[Finding]:
    catalog = load_span_catalog()
    exact, dynamic, _unresolved = _span_sites(ctx)
    used = {name for _sf, _l, name in exact}
    for _sf, _l, prefix in dynamic:
        if prefix:
            used |= {s for s in catalog if s.startswith(prefix)}
    anchor = ctx.by_rel('kyverno_tpu/observability/catalog.py')

    def locate(name):
        target = anchor if anchor is not None else ctx.files[0]
        line = 1
        if anchor is not None:
            for i, text in enumerate(anchor.lines, start=1):
                if f"'{name}'" in text:
                    line = i
                    break
        return target, line

    for name in sorted(catalog):
        if name in used:
            continue
        target, line = locate(name)
        yield target.finding(
            'KTPU505', line,
            f'span catalog: {name!r} has no start site in the tree — '
            f'remove the entry or add the span')


# -- fleet-scope hygiene (KTPU509) --------------------------------------------

def load_fleet_scopes() -> Dict[str, str]:
    """Cataloged metrics that declare a ``fleet_scope`` — the identity
    label key every write site must pass so cross-host federation can
    tell the series apart."""
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from kyverno_tpu.observability.catalog import METRICS
    return {name: m.fleet_scope for name, m in METRICS.items()
            if getattr(m, 'fleet_scope', '')}


def collect_labeled_writes(files: List[SourceFile]
                           ) -> List[Tuple[SourceFile, int, str,
                                           Optional[frozenset]]]:
    """Resolved metric write sites with the label keys they pass:
    ``[(file, line, metric_name, label_keys)]``.  ``label_keys`` is
    None when the site splats ``**labels`` (uncheckable keys)."""
    all_consts: Dict[str, str] = {}
    for sf in files:
        if sf.tree is not None:
            all_consts.update(_consts(sf))
    sites: List[Tuple[SourceFile, int, str, Optional[frozenset]]] = []
    for sf in files:
        if sf.tree is None:
            continue
        local_consts = _consts(sf)
        for node in sf.walk():
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in WRITE_METHODS and node.args):
                continue
            arg = node.args[0]
            name: Optional[str] = None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
            elif isinstance(arg, ast.Name):
                name = local_consts.get(arg.id, all_consts.get(arg.id))
            elif isinstance(arg, ast.Attribute):
                name = all_consts.get(arg.attr)
            if name is None:
                continue  # KTPU502's finding, not ours
            keys: Optional[frozenset]
            if any(kw.arg is None for kw in node.keywords):
                keys = None  # **labels splat — keys unknowable
            else:
                keys = frozenset(kw.arg for kw in node.keywords)
            sites.append((sf, node.lineno, name, keys))
    return sites


@register('KTPU509', 'fleet-scope hygiene: a parallel/ metric write '
                     'with no shard/host identity scope, a scoped '
                     'write missing its identity label, or a dead '
                     'fleet_scope')
def _check_fleet_scope(ctx: Context) -> Iterable[Finding]:
    scopes = load_fleet_scopes()
    sites = ctx.cached('catalog:labeled',
                       lambda: collect_labeled_writes(ctx.files))
    exercised: set = set()
    for sf, line, name, keys in sites:
        rel = '/' + sf.rel.replace(os.sep, '/')
        in_parallel = '/parallel/' in rel
        scope = scopes.get(name)
        if in_parallel:
            if scope is None:
                yield sf.finding(
                    'KTPU509', line,
                    f'metric {name!r} is written from parallel/ but '
                    f'declares no fleet_scope in the catalog — '
                    f'without a shard/host identity label the '
                    f'cross-host federation merges every process '
                    f'into one series')
                continue
            exercised.add(name)
        if scope is not None and keys is not None and scope not in keys:
            yield sf.finding(
                'KTPU509', line,
                f'metric {name!r} declares fleet_scope='
                f'{scope!r} but this write site passes no '
                f'{scope}=... label — the federated series from '
                f'different shards/meshes would collide')
    anchor = ctx.by_rel('kyverno_tpu/observability/catalog.py')

    def locate(name):
        target = anchor if anchor is not None else ctx.files[0]
        line = 1
        if anchor is not None:
            for i, text in enumerate(anchor.lines, start=1):
                if f"'{name}'" in text:
                    line = i
                    break
        return target, line

    for name in sorted(scopes):
        if name in exercised:
            continue
        target, line = locate(name)
        yield target.finding(
            'KTPU509', line,
            f'catalog: {name} declares fleet_scope='
            f'{scopes[name]!r} but no parallel/ write site exercises '
            f'it — drop the scope or move the emitter onto the mesh '
            f'path')


# -- pipeline stage registry (KTPU507) ----------------------------------------

def load_stage_registry() -> Dict[str, str]:
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from kyverno_tpu.observability.catalog import PIPELINE_STAGES
    return dict(PIPELINE_STAGES)


def collect_stage_labels(files: List[SourceFile]
                         ) -> List[Tuple[SourceFile, int, str]]:
    """Pipeline stage-label sites across a parsed file set:
    ``stage('<s>')`` timers, ``add_backpressure('<s>', ...)``
    attributions, ``exec_scope(tl, c, '<s>')`` inline wrappers, and the
    literal ``(name, fn)`` stage lists handed to ``ChunkPipeline``.
    Non-literal labels are skipped (variables flow from these same
    literal surfaces)."""
    sites: List[Tuple[SourceFile, int, str]] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in sf.walk():
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) else \
                (func.id if isinstance(func, ast.Name) else '')
            if attr in ('stage', 'add_backpressure'):
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    sites.append((sf, node.lineno, arg.value))
            elif attr == 'exec_scope' and len(node.args) >= 3:
                arg = node.args[2]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    sites.append((sf, node.lineno, arg.value))
            elif attr == 'ChunkPipeline':
                arg = node.args[0]
                if isinstance(arg, (ast.List, ast.Tuple)):
                    for elt in arg.elts:
                        if isinstance(elt, ast.Tuple) and elt.elts and \
                                isinstance(elt.elts[0], ast.Constant) and \
                                isinstance(elt.elts[0].value, str):
                            sites.append((sf, elt.lineno,
                                          elt.elts[0].value))
    return sites


@register('KTPU507', 'pipeline stage label in compiler/ missing from '
                     'the stage registry (catalog PIPELINE_STAGES), '
                     'or a registered stage no code uses')
def _check_stage_labels(ctx: Context) -> Iterable[Finding]:
    registry = load_stage_registry()
    sites = collect_stage_labels(ctx.files)
    for sf, line, label in sites:
        if label in registry:
            continue
        rel = '/' + sf.rel.replace(os.sep, '/')
        if '/compiler/' in rel:
            yield sf.finding(
                'KTPU507', line,
                f'stage label {label!r} is not a registered pipeline '
                f'stage (observability/catalog.py PIPELINE_STAGES) — '
                f'register it, or the timeline critical-path walk and '
                f'the blame metric silently drop its intervals')
    used = {label for _sf, _l, label in sites}
    anchor = ctx.by_rel('kyverno_tpu/observability/catalog.py')

    def locate(name):
        target = anchor if anchor is not None else ctx.files[0]
        line = 1
        if anchor is not None:
            for i, text in enumerate(anchor.lines, start=1):
                if f"'{name}'" in text:
                    line = i
                    break
        return target, line

    for name in sorted(registry):
        if name in used:
            continue
        target, line = locate(name)
        yield target.finding(
            'KTPU507', line,
            f'stage registry: {name!r} has no stage()/exec_scope/'
            f'ChunkPipeline/add_backpressure site in the tree — '
            f'remove the entry or add the stage')


# -- unit-mismatch pass (KTPU506) ---------------------------------------------

#: registry writes that carry a measured value (register_histogram
#: takes buckets, clear_gauge takes nothing — neither can mismatch)
_VALUE_METHODS = {'inc', 'observe', 'set_gauge'}


def _metric_unit(name: str) -> Optional[str]:
    """'seconds' | 'bytes' when the metric name declares a unit."""
    base = name[:-len('_total')] if name.endswith('_total') else name
    if base.endswith('_seconds'):
        return 'seconds'
    if base.endswith('_bytes'):
        return 'bytes'
    return None


def _iter_scopes(tree: ast.Module):
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _scope_nodes(scope: ast.AST):
    """Every node in ``scope`` excluding nested function bodies (each
    nested function is visited as its own scope)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _value_arg(call: ast.Call) -> Optional[ast.AST]:
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg in ('value', 'amount', 'seconds'):
            return kw.value
    return None  # inc() with the implicit 1.0 — no unit to carry


def _ms_name(expr: ast.AST) -> Optional[str]:
    """A terminal ``*_ms`` name/attribute inside ``expr``, if any."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id.endswith('_ms'):
            return node.id
        if isinstance(node, ast.Attribute) and node.attr.endswith('_ms'):
            return node.attr
    return None


def _has_ms_conversion(expr: ast.AST) -> bool:
    """True when ``expr`` contains a ms→s conversion (``/ 1000`` or
    ``* 0.001`` against a constant)."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.BinOp):
            continue
        if isinstance(node.op, ast.Div) and \
                isinstance(node.right, ast.Constant) and \
                node.right.value in (1000, 1000.0):
            return True
        if isinstance(node.op, ast.Mult):
            for side in (node.left, node.right):
                if isinstance(side, ast.Constant) and \
                        side.value == 0.001:
                    return True
    return False


def _is_str_expr(expr: ast.AST) -> bool:
    """Conservatively: does ``expr`` evaluate to a str?"""
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, str)
    if isinstance(expr, ast.JoinedStr):
        return True
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name) and f.id in ('str', 'repr'):
            return True
        if isinstance(f, ast.Attribute) and \
                f.attr in ('decode', 'dumps', 'format', 'join'):
            return True
    return False


def _str_len_call(expr: ast.AST, bindings: Dict[str, ast.AST]
                  ) -> bool:
    """``len(<str-valued expression>)`` anywhere in ``expr``, with the
    len argument resolved one level through local assignments."""
    for node in ast.walk(expr):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Name) and
                node.func.id == 'len' and node.args):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Name):
            arg = bindings.get(arg.id, arg)
        if _is_str_expr(arg):
            return True
    return False


@register('KTPU506', 'unit mismatch: a *_seconds/*_bytes metric '
                     'written from a *_ms value (no /1000) or a '
                     'len() of a str')
def _check_unit_mismatch(ctx: Context) -> Iterable[Finding]:
    from .retrace import _scope_bindings
    all_consts: Dict[str, str] = {}
    for sf in ctx.files:
        if sf.tree is not None:
            all_consts.update(_consts(sf))
    for sf in ctx.files:
        if sf.tree is None:
            continue
        local_consts = _consts(sf)

        def _unit_of(node):
            arg = node.args[0]
            name: Optional[str] = None
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                name = arg.value
            elif isinstance(arg, ast.Name):
                name = local_consts.get(arg.id, all_consts.get(arg.id))
            elif isinstance(arg, ast.Attribute):
                name = all_consts.get(arg.attr)
            return (name, _metric_unit(name)
                    if name is not None else None)

        # cheap pre-filter off the per-file node index: the expensive
        # per-scope binding walk only runs for the handful of files
        # that write a unit-suffixed metric at all
        if not any(isinstance(n.func, ast.Attribute) and
                   n.func.attr in _VALUE_METHODS and n.args and
                   _unit_of(n)[1] is not None
                   for n in sf.nodes_of(ast.Call)):
            continue
        for scope in _iter_scopes(sf.tree):
            bindings = _scope_bindings(scope)
            for node in _scope_nodes(scope):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr in _VALUE_METHODS and node.args):
                    continue
                name, unit = _unit_of(node)
                if unit is None:
                    continue
                value = _value_arg(node)
                if value is None:
                    continue
                # one-level local-dataflow resolution (KTPU204 depth):
                # a bare name checks its own spelling AND what it was
                # assigned from in this scope
                exprs = [value]
                if isinstance(value, ast.Name):
                    resolved = bindings.get(value.id)
                    if resolved is not None:
                        exprs.append(resolved)
                if unit == 'seconds':
                    for expr in exprs:
                        ms = _ms_name(expr)
                        if ms is not None and \
                                not any(_has_ms_conversion(e)
                                        for e in exprs):
                            yield sf.finding(
                                'KTPU506', node.lineno,
                                f'{name} is a seconds metric but its '
                                f'value derives from {ms!r} with no '
                                f'/1000 conversion — milliseconds '
                                f'exported as seconds are off by '
                                f'1000x on every consumer')
                            break
                elif unit == 'bytes':
                    if any(_str_len_call(e, bindings) for e in exprs):
                        yield sf.finding(
                            'KTPU506', node.lineno,
                            f'{name} is a bytes metric but its value '
                            f'is len() of a str — that counts '
                            f'characters, not bytes; len(s.encode()) '
                            f'measures the wire size')


# -- partition key-hygiene pass (KTPU508) -------------------------------------

def _fingerprint_arg(call: ast.Call) -> Optional[ast.AST]:
    """The fingerprint operand of an ``executable_cache_key`` call
    (first positional, or the ``fingerprint=`` keyword)."""
    for kw in call.keywords:
        if kw.arg == 'fingerprint':
            return kw.value
    if call.args:
        return call.args[0]
    return None


def _contains_set_fingerprint(expr: ast.AST) -> bool:
    from .retrace import _callee_name
    return any(isinstance(n, ast.Call) and
               _callee_name(n.func) == 'policy_set_fingerprint'
               for n in ast.walk(expr))


@register('KTPU508', 'compile/AOT key construction outside partition/ '
                     'consumes the whole-set fingerprint '
                     '(policy_set_fingerprint) — one policy edit would '
                     'invalidate every partition\'s executables')
def _check_partition_key_hygiene(ctx: Context) -> Iterable[Finding]:
    """``executable_cache_key`` callers must take their fingerprint
    from ``partition/keys.compile_fingerprint`` (which scopes it to the
    policies actually compiled into the evaluator), never directly from
    ``policy_set_fingerprint`` over the whole set — that spelling works
    until the first partitioned build, then silently degrades every
    policy edit back to a recompile-the-world.  ``partition/`` itself
    is the sanctioned authority and is exempt.  The fingerprint operand
    resolves one level through enclosing-scope bindings (KTPU204
    depth), innermost scope first — the binding feeding a nested
    closure's name may live in the enclosing builder function
    (``ops/eval.py:build_evaluator``)."""
    from .retrace import _callee_name, _scope_bindings
    for sf in ctx.files:
        if sf.tree is None:
            continue
        rel = '/' + sf.rel.replace(os.sep, '/')
        if '/partition/' in rel:
            continue
        sites: List[Tuple[List[ast.AST], ast.Call]] = []

        def visit(node: ast.AST, chain: List[ast.AST]) -> None:
            for child in ast.iter_child_nodes(node):
                inner = chain
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    inner = chain + [child]
                if isinstance(child, ast.Call) and \
                        _callee_name(child.func) == \
                        'executable_cache_key':
                    sites.append((chain, child))
                visit(child, inner)

        visit(sf.tree, [sf.tree])
        for chain, call in sites:
            expr = _fingerprint_arg(call)
            if expr is None:
                continue
            if isinstance(expr, ast.Name):
                resolved = None
                for scope in reversed(chain):
                    resolved = _scope_bindings(scope).get(expr.id)
                    if resolved is not None:
                        break
                if resolved is None:
                    continue  # parameter / out-of-scope: undecidable
                expr = resolved
            if _contains_set_fingerprint(expr):
                yield sf.finding(
                    'KTPU508', call,
                    'executable cache key consumes the whole-set '
                    'fingerprint (policy_set_fingerprint) outside '
                    'partition/ — draw it from '
                    'partition/keys.compile_fingerprint so partitioned '
                    'builds key executables per partition')


def render_span_table() -> str:
    """The README span table, generated from the catalog so docs
    cannot drift from it (mirrors the knob table)."""
    rows = ['| Span | Covers |', '|---|---|']
    catalog = load_span_catalog()
    for name in sorted(catalog):
        rows.append(f'| `{name}` | {catalog[name]} |')
    return '\n'.join(rows)


# -- standalone API for the scripts/check_metric_names.py shim ---------------

def default_sources() -> List[str]:
    """The checker file set, rooted at the repo — one list
    (``core.DEFAULT_SOURCE_PATHS``) shared with ``scripts/analyze.py``
    so the standalone catalog checker and the driver can't drift."""
    from .core import DEFAULT_SOURCE_PATHS
    return [os.path.join(REPO_ROOT, p) for p in DEFAULT_SOURCE_PATHS]


def collect_call_sites() -> Tuple[List[Tuple[str, int, str]],
                                  List[Tuple[str, int, str]]]:
    """Original shim signature: (resolved [(relpath, line, name)],
    unresolved [(relpath, line, desc)]), walking the real tree fresh
    on every call."""
    from .core import collect_files
    files = collect_files(default_sources(), REPO_ROOT)
    resolved, unresolved = collect_from_files(files)
    return ([(sf.rel, line, name) for sf, line, name in resolved],
            [(sf.rel, line, desc) for sf, line, desc in unresolved])


def check_main() -> int:
    """Exit-code semantics of the original standalone checker."""
    catalog = load_catalog()
    resolved, unresolved = collect_call_sites()
    errors: List[str] = []
    for name, (mtype, mhelp) in catalog.items():
        if mtype not in ('counter', 'gauge', 'histogram'):
            errors.append(f'catalog: {name} has invalid type {mtype!r}')
        if not mhelp.strip():
            errors.append(f'catalog: {name} has empty help text')
    used = {name for _r, _l, name in resolved}
    for rel, line, name in resolved:
        if name not in catalog:
            errors.append(
                f'{rel}:{line}: metric {name!r} not in '
                f'observability/catalog.py')
    for rel, line, desc in unresolved:
        errors.append(
            f'{rel}:{line}: metric name is not a literal or module '
            f'constant ({desc}) — uncheckable, use a constant')
    for name in catalog:
        if name not in used and name not in DEAD_METRIC_ALLOWLIST:
            errors.append(
                f'catalog: {name} has no write site in the tree — '
                f'remove the entry, add the emitter, or allowlist it '
                f'with a reason (DEAD_METRIC_ALLOWLIST)')
    for name, problem in stale_allowlist_entries(catalog, used):
        errors.append(f'DEAD_METRIC_ALLOWLIST: {name} {problem} — '
                      f'drop the stale allowlist entry')
    if not resolved:
        errors.append('no metric call sites found — checker is broken')
    if errors:
        for e in errors:
            print(e, file=sys.stderr)
        return 1
    print(f'ok: {len(resolved)} call sites over {len(used)} metrics, '
          f'{len(catalog)} cataloged')
    return 0
