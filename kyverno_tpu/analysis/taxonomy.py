"""Fallback-taxonomy passes (KTPU3xx).

PR 3's coverage ledger only works if every host fallback is
*attributed*: a ``CompileError`` / ``FALLBACK`` / ``_HOST_MARKER`` site
that names no taxonomy reason shows up in dashboards as ``unknown``,
and a taxonomy reason with no raise site is documentation fiction.
Both are program-structure properties — enforced here, statically.

* **KTPU301** — a ``reason`` handed to a fallback-recording call
  (``CompileError``, ``_fallback``, ``tally.fallback``,
  ``coverage.record_fallback``, ``host_rule``, ``record_scan``) is not
  a member of the ``observability/coverage.py`` taxonomy (string
  literals and ``REASON_*`` constant references are both resolved).
* **KTPU302** — a bare ``return <SENTINEL>`` (``FALLBACK`` /
  ``_HOST_MARKER`` — any module-level ``X = object()`` sentinel) in a
  ``compiler/`` or ``mutate/`` (device-side mutate) file whose
  enclosing function never attributes a reason: the fallback escapes
  the ledger.
* **KTPU303** — dead reason: a taxonomy member no site ever raises
  (mirrors the dead-metric pass).
* **KTPU304** — a broad ``except Exception`` in a serving-path file
  (any ``serving/`` component, or a ``pipeline.py``) that neither
  re-raises nor records a shed/fallback reason: the never-500
  discipline says every swallowed serving error must land on a ledger
  somewhere, or degradation becomes silent.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, Optional, Set, Tuple

from .core import Context, Finding, register
from .jitgraph import jit_graph, walk_scope

#: reason-carrying calls: callee name → (positional index, kwarg name)
REASON_CALLS: Dict[str, Tuple[int, str]] = {
    'CompileError': (1, 'reason'),
    '_fallback': (0, 'reason'),
    'fallback': (1, 'reason'),
    'record_fallback': (1, 'reason'),
    'host_rule': (2, 'reason'),
    'record_scan': (3, 'reason'),
}

#: attribution calls that mark an enclosing function as ledger-aware
ATTRIBUTING_CALLS = {'_fallback', 'fallback', 'record_fallback',
                     'host_rule'}

COVERAGE_REL = os.path.join('kyverno_tpu', 'observability', 'coverage.py')
COVERAGE_MODULE = 'kyverno_tpu.observability.coverage'


def load_taxonomy(ctx: Context) -> Dict[str, str]:
    """``REASON_*`` constant name → slug, parsed from coverage.py's AST
    (the analyzed tree's copy when present, the installed one
    otherwise — fixture trees validate against the real taxonomy)."""
    def build():
        sf = ctx.by_rel(COVERAGE_REL.replace(os.sep, '/')) or \
            ctx.by_rel(COVERAGE_REL)
        if sf is not None and sf.tree is not None:
            tree = sf.tree
        else:
            path = os.path.join(os.path.dirname(__file__), '..',
                                'observability', 'coverage.py')
            with open(path, encoding='utf-8') as f:
                tree = ast.parse(f.read())
        consts: Dict[str, str] = {}
        members: Optional[Set[str]] = None
        for node in tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name) and \
                            t.id.startswith('REASON_'):
                        consts[t.id] = node.value.value
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    getattr(node.value.func, 'id', '') == 'frozenset' and \
                    any(getattr(t, 'id', '') == 'REASONS'
                        for t in node.targets):
                members = set()
                for leaf in ast.walk(node.value):
                    if isinstance(leaf, ast.Name) and \
                            leaf.id.startswith('REASON_'):
                        members.add(leaf.id)
        if members is not None:
            consts = {k: v for k, v in consts.items() if k in members}
        return consts
    return ctx.cached('taxonomy', build)


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _reason_arg(call: ast.Call) -> Optional[ast.AST]:
    name = _callee_name(call.func)
    if name not in REASON_CALLS:
        return None
    pos, kw = REASON_CALLS[name]
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _is_coverage_ref(mi, node: ast.AST) -> Optional[str]:
    """``REASON_*`` constant name when ``node`` references one through
    the coverage module (imported name or module-attribute access)."""
    if isinstance(node, ast.Name):
        imp = mi.imports.get(node.id)
        if imp and imp[0] == 'from' and imp[1] == COVERAGE_MODULE:
            return imp[2]
        return None
    if isinstance(node, ast.Attribute) and \
            node.attr.startswith('REASON_') and \
            isinstance(node.value, ast.Name):
        imp = mi.imports.get(node.value.id)
        if imp and ((imp[0] == 'module' and imp[1] == COVERAGE_MODULE) or
                    (imp[0] == 'from' and
                     f'{imp[1]}.{imp[2]}' == COVERAGE_MODULE)):
            return node.attr
    return None


@register('KTPU301', 'fallback reason outside the '
                     'observability/coverage.py taxonomy')
def _check_reason_values(ctx: Context) -> Iterable[Finding]:
    taxonomy = load_taxonomy(ctx)
    slugs = set(taxonomy.values())
    graph = jit_graph(ctx)
    for rel, mi in graph.modules.items():
        if rel.replace(os.sep, '/').endswith(
                'observability/coverage.py'):
            continue
        for node in mi.sf.walk():
            if not isinstance(node, ast.Call):
                continue
            arg = _reason_arg(node)
            if arg is None:
                continue
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                if arg.value not in slugs:
                    yield mi.sf.finding(
                        'KTPU301', node,
                        f'reason {arg.value!r} is not in the coverage '
                        f'taxonomy — use a slug from '
                        f'observability/coverage.py REASONS')
            else:
                const = _is_coverage_ref(mi, arg)
                if const is not None and const not in taxonomy:
                    yield mi.sf.finding(
                        'KTPU301', node,
                        f'`{const}` is not a taxonomy constant in '
                        f'observability/coverage.py')


def _sentinel_names(ctx: Context) -> Set[str]:
    """Module-level ``X = object()`` *fallback* sentinel names across
    the tree.  Only names that read as fallback markers count
    (``FALLBACK`` / ``*HOST*``) — encoder-internal sentinels like
    ``_MISSING`` mark absent values, not host escapes."""
    def build():
        out: Set[str] = set()
        for sf in ctx.files:
            if sf.tree is None:
                continue
            for node in sf.tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        getattr(node.value.func, 'id', '') == 'object' \
                        and not node.value.args:
                    for t in node.targets:
                        if isinstance(t, ast.Name) and (
                                'FALLBACK' in t.id or 'HOST' in t.id):
                            out.add(t.id)
        return out
    return ctx.cached('sentinels', build)


def _attributes_reason(fn: ast.AST) -> bool:
    for node in walk_scope(fn):
        if isinstance(node, ast.Call) and \
                _callee_name(node.func) in ATTRIBUTING_CALLS:
            return True
        if isinstance(node, ast.Raise) and \
                isinstance(node.exc, ast.Call) and \
                _callee_name(node.exc.func) == 'CompileError':
            return True
    return False


@register('KTPU302', 'unattributed host-fallback site in compiler/ or '
                     'mutate/ (bare sentinel return with no taxonomy '
                     'reason)')
def _check_unattributed_fallback(ctx: Context) -> Iterable[Finding]:
    sentinels = _sentinel_names(ctx)
    graph = jit_graph(ctx)
    for rel, mi in graph.modules.items():
        parts = rel.replace(os.sep, '/').split('/')
        # compiler/ plus the device-side mutate package (its lowering
        # shares the FALLBACK discipline); engine/mutate/ is the host
        # oracle and carries no sentinels
        if 'compiler' not in parts and \
                not ('mutate' in parts and 'engine' not in parts):
            continue
        for defs in mi.defs.values():
            for fn in defs:
                attributes = None  # computed lazily per function
                for node in walk_scope(fn):
                    if not (isinstance(node, ast.Return) and
                            isinstance(node.value, ast.Name) and
                            node.value.id in sentinels):
                        continue
                    if attributes is None:
                        attributes = _attributes_reason(fn)
                    if not attributes:
                        yield mi.sf.finding(
                            'KTPU302', node,
                            f'`return {node.value.id}` in `{fn.name}` '
                            f'records no taxonomy reason — attribute '
                            f'via _fallback()/tally.fallback()/'
                            f'coverage.record_fallback()')


@register('KTPU303', 'dead taxonomy reason: no raise/record site '
                     'anywhere in the tree')
def _check_dead_reasons(ctx: Context) -> Iterable[Finding]:
    taxonomy = load_taxonomy(ctx)
    if not taxonomy:
        return
    used: Set[str] = set()
    graph = jit_graph(ctx)
    for rel, mi in graph.modules.items():
        if rel.replace(os.sep, '/').endswith(
                'observability/coverage.py'):
            continue
        for node in mi.sf.walk():
            if isinstance(node, ast.Call):
                arg = _reason_arg(node)
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    used.add(arg.value)
            const = _is_coverage_ref(mi, node) \
                if isinstance(node, (ast.Name, ast.Attribute)) else None
            if const is not None and const in taxonomy:
                used.add(taxonomy[const])
    cov = ctx.by_rel(COVERAGE_REL.replace(os.sep, '/'))
    for const, slug in sorted(taxonomy.items()):
        if slug in used:
            continue
        line = 1
        if cov is not None and cov.tree is not None:
            for node in cov.tree.body:
                if isinstance(node, ast.Assign) and any(
                        getattr(t, 'id', '') == const
                        for t in node.targets):
                    line = node.lineno
                    break
        anchor = cov if cov is not None else ctx.files[0]
        yield anchor.finding(
            'KTPU303', line,
            f'taxonomy reason {slug!r} ({const}) has no raise/record '
            f'site — remove it or wire the fallback that should '
            f'carry it')


#: calls inside an ``except Exception`` handler that prove the
#: failure was attributed instead of silently swallowed: shed-ledger
#: records, coverage records, and the batcher's quarantine entry
#: points (which shed transitively per isolated row)
SHED_CALLS = {'shed', '_try_shed', 'record', 'record_shed',
              'record_fallback', '_shed_batch', '_quarantine'}


def _is_broad_except(node: ast.ExceptHandler) -> bool:
    names = []
    if node.type is None:
        return True  # bare except:
    for leaf in ast.walk(node.type):
        if isinstance(leaf, ast.Name):
            names.append(leaf.id)
        elif isinstance(leaf, ast.Attribute):
            names.append(leaf.attr)
    return 'Exception' in names or 'BaseException' in names


def _handler_attributes(node: ast.ExceptHandler) -> bool:
    for stmt in node.body:
        for leaf in ast.walk(stmt):
            if isinstance(leaf, ast.Raise):
                return True
            if isinstance(leaf, ast.Call) and \
                    _callee_name(leaf.func) in SHED_CALLS:
                return True
    return False


@register('KTPU304', 'serving-path `except Exception` that neither '
                     'records a shed reason nor re-raises')
def _check_swallowed_serving_errors(ctx: Context) -> Iterable[Finding]:
    graph = jit_graph(ctx)
    for rel, mi in graph.modules.items():
        parts = rel.replace(os.sep, '/').split('/')
        if 'serving' not in parts and parts[-1] != 'pipeline.py':
            continue
        for node in mi.sf.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_except(node) or _handler_attributes(node):
                continue
            yield mi.sf.finding(
                'KTPU304', node,
                'broad except on the serving path neither re-raises '
                'nor records a shed/fallback reason — a swallowed '
                'serving error is silent degradation; attribute it '
                'via the shed ledger or coverage.record_fallback()')
