"""Concurrency-discipline passes (KTPU6xx) on the resolved call graph.

The serving and observability layers hand-maintain a set of thread
invariants that reviews keep re-litigating: worker threads must
re-install the ambient ``ScanCapture``/span before touching the
device path (PRs 11/16), residency gauges must be marked
``mark_reset_on_close`` so a drained server exports 0 (PR 13), and
shared attributes written from background threads need the same lock
their other writers hold.  With the v2 binder these are mechanical
reachability questions over ``Thread(target=...)`` roots, so they are
rules now:

* **KTPU601** — a module/instance attribute written from a
  ``Thread(target=...)``-reachable function while holding no lock
  that any *other* writer of the same attribute holds.  Lock context
  is lexical (``with self._lock:`` in the same function); writes in
  ``__init__`` are construction-time and don't count as a competing
  writer.  Scoped to classes that *own* a lock-typed attribute —
  a lockless class is declaring thread confinement, and flagging
  every such write would drown the signal (the rule checks lock
  *discipline*, not the absence of a threading design).
* **KTPU602** — a thread target whose reachable set records stage
  spans (``stage(...)`` / ``exec_scope(...)``) but never re-installs
  telemetry (``install_capture`` / ``install_span`` /
  ``ScanCapture``) — the worker's device work would record into no
  capture and parent to no request span.
* **KTPU603** — a residency-patterned gauge (``set_gauge`` from a
  loop or a thread-reachable worker) whose metric is never
  ``mark_reset_on_close``-marked (and never explicitly retracted via
  ``clear_gauge``) — a drained server would export the last sample
  forever.
* **KTPU604** — lock acquisition-order inversion: two locks the
  binder can identify acquired in both ``A→B`` and ``B→A`` order
  (nested ``with`` in one function, or one call edge deep).

All four passes share the binder's receiver typing: a "lock" is an
attribute or module var assigned from ``threading.Lock`` / ``RLock``
/ ``Condition``, identified as ``(ClassName, attr)`` or
``(module, name)`` — the same instance-attribute identity the code
uses."""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Context, Finding, register
from .jitgraph import FuncKey, JitGraph, ModuleInfo, jit_graph

#: constructor names that produce a mutual-exclusion object
_LOCK_CTORS = {'Lock', 'RLock', 'Condition', 'Semaphore',
               'BoundedSemaphore'}

#: calls that record into the ambient stage-span machinery
_STAGE_CALLS = {'stage', 'exec_scope'}

#: calls that (re-)install the ambient telemetry on a thread
_INSTALL_CALLS = {'install_capture', 'install_span', 'ScanCapture'}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

LockId = Tuple  # ('attr', ClassName, attr) | ('module', rel, name)


def _is_lock_token(tok: Optional[Tuple]) -> bool:
    if tok is None:
        return False
    if tok[0] == 'local':
        return tok[1] in _LOCK_CTORS
    if tok[0] == 'attr':
        return tok[2] in _LOCK_CTORS
    return False


def _enclosing_function(mi: ModuleInfo,
                        node: ast.AST) -> Optional[ast.AST]:
    cur = mi.parents.get(node)
    while cur is not None:
        if isinstance(cur, _FUNC_DEFS):
            return cur
        cur = mi.parents.get(cur)
    return None


def _enclosing_class_name(mi: ModuleInfo,
                          node: ast.AST) -> Optional[str]:
    cur = mi.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = mi.parents.get(cur)
    return None


class ThreadModel:
    """Shared KTPU6xx state: thread roots, lock identities, per-node
    lexical lock context — built once per Context on top of the
    binder."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.graph: JitGraph = jit_graph(ctx)
        # (root FuncKey, target mi, target fn, Thread() call, site sf)
        self.roots: List[Tuple] = []
        self._lock_withs_cache: Dict[FuncKey, List] = {}
        self._find_thread_roots()
        self.thread_reachable: Set[FuncKey] = set()
        self._root_reach: Dict[int, Set[FuncKey]] = {}
        for i, (_k, tmi, tfn, _call, _sf) in enumerate(self.roots):
            reach = self.graph.reachable_set(tmi, tfn)
            self._root_reach[i] = reach
            self.thread_reachable |= reach

    # -- thread roots --------------------------------------------------------

    @staticmethod
    def _is_thread_ctor(func: ast.AST) -> bool:
        if isinstance(func, ast.Name):
            return func.id == 'Thread'
        if isinstance(func, ast.Attribute):
            return func.attr == 'Thread'
        return False

    def _find_thread_roots(self) -> None:
        g = self.graph
        for mi in g.modules.values():
            for node in mi.sf.nodes_of(ast.Call):
                if not self._is_thread_ctor(node.func):
                    continue
                target = next((kw.value for kw in node.keywords
                               if kw.arg == 'target'), None)
                if target is None:
                    continue
                fn = _enclosing_function(mi, node)
                resolved: List[Tuple[ModuleInfo, ast.AST]] = []
                if isinstance(target, ast.Name):
                    resolved = [(mi, d)
                                for d in mi.defs.get(target.id, [])]
                    if not resolved:
                        imp = mi.imports.get(target.id)
                        if imp is not None and imp[0] == 'from':
                            tgt = g.by_dotted.get(imp[1])
                            if tgt is not None:
                                resolved = [(tgt, d) for d in
                                            tgt.defs.get(imp[2], [])]
                elif isinstance(target, ast.Attribute):
                    resolved = g._resolve_attr_call(mi, fn, target)
                for tmi, tfn in resolved:
                    self.roots.append(
                        ((tmi.sf.rel, tfn.lineno), tmi, tfn, node,
                         mi.sf))

    # -- lock identity -------------------------------------------------------

    def lock_id(self, mi: ModuleInfo, fn: Optional[ast.AST],
                expr: ast.AST) -> Optional[LockId]:
        """Identity of a ``with <expr>:`` context manager when the
        binder can prove it's a lock."""
        g = self.graph
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base == 'self':
                cls = _enclosing_class_name(mi, fn) \
                    if fn is not None else None
                if cls is not None:
                    ci = mi.classes.get(cls)
                    if ci is not None and \
                            _is_lock_token(ci.attr_types.get(expr.attr)):
                        return ('attr', cls, expr.attr)
                return None
            tok = g._receiver_token(mi, fn, expr.value)
            if tok is not None:
                resolved = g._resolve_class(mi, tok)
                if resolved is not None:
                    tmi, ci = resolved
                    if _is_lock_token(ci.attr_types.get(expr.attr)):
                        return ('attr', ci.name, expr.attr)
            return None
        if isinstance(expr, ast.Name):
            if _is_lock_token(mi.var_types.get(expr.id)):
                return ('module', mi.sf.rel, expr.id)
            if fn is not None and _is_lock_token(
                    g._local_types(mi, fn).get(expr.id)):
                return ('module', mi.sf.rel, expr.id)
        return None

    def held_locks(self, mi: ModuleInfo, fn: ast.AST,
                   node: ast.AST) -> Set[LockId]:
        """Locks lexically held at ``node`` (``with`` ancestors inside
        the same function)."""
        out: Set[LockId] = set()
        cur = mi.parents.get(node)
        while cur is not None and not isinstance(cur, _FUNC_DEFS):
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    lid = self.lock_id(mi, fn, item.context_expr)
                    if lid is not None:
                        out.add(lid)
            cur = mi.parents.get(cur)
        return out

    def fn_lock_withs(self, mi: ModuleInfo, fn: ast.AST
                      ) -> List[Tuple[ast.AST, List[LockId]]]:
        """``with`` statements in ``fn`` that acquire provable locks,
        with their per-item identities in acquisition order
        (memoized)."""
        key = (mi.sf.rel, fn.lineno)
        hit = self._lock_withs_cache.get(key)
        if hit is not None:
            return hit
        out = []
        for node in self.graph.scope_nodes(mi, fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                ids = []
                for item in node.items:
                    lid = self.lock_id(mi, fn, item.context_expr)
                    if lid is not None:
                        ids.append(lid)
                if ids:
                    out.append((node, ids))
        self._lock_withs_cache[key] = out
        return out


def thread_model(ctx: Context) -> ThreadModel:
    return ctx.cached('threadmodel', lambda: ThreadModel(ctx))


def _lock_name(lid: LockId) -> str:
    if lid[0] == 'attr':
        return f'{lid[1]}.{lid[2]}'
    return f'{lid[1]}:{lid[2]}'


# -- KTPU601: unlocked shared-attribute write from a thread ------------------

@register('KTPU601', 'attribute written from a Thread-reachable '
                     'function without holding a lock shared with '
                     'its other writers')
def _check_unlocked_write(ctx: Context) -> Iterable[Finding]:
    tm = thread_model(ctx)
    g = tm.graph
    # identity -> list of (fn key, fn node, write node, mi, locks)
    # One pass over the per-file assignment index; a write belongs to
    # its *innermost* enclosing function — the same attribution
    # walk_scope gives (it never descends into nested defs).
    writers: Dict[Tuple, List[Tuple]] = {}
    for mi in g.modules.values():
        globals_memo: Dict[int, Set[str]] = {}
        for node in mi.sf.nodes_of(ast.Assign, ast.AugAssign):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            cands = [t for t in targets
                     if (isinstance(t, ast.Attribute) and
                         isinstance(t.value, ast.Name) and
                         t.value.id == 'self')
                     or isinstance(t, ast.Name)]
            if not cands:
                continue
            fn = _enclosing_function(mi, node)
            if fn is None or fn.name in ('__init__', '__new__',
                                         '__del__'):
                continue
            fkey = (mi.sf.rel, fn.lineno)
            for t in cands:
                ident = None
                if isinstance(t, ast.Attribute):
                    cls = _enclosing_class_name(mi, fn)
                    ci = mi.classes.get(cls) \
                        if cls is not None else None
                    if ci is not None and any(
                            _is_lock_token(tok) for tok in
                            ci.attr_types.values()):
                        ident = ('attr', mi.sf.rel, cls, t.attr)
                else:
                    declared = globals_memo.get(id(fn))
                    if declared is None:
                        declared = set()
                        for g_node in g.scope_nodes(mi, fn):
                            if isinstance(g_node, ast.Global):
                                declared.update(g_node.names)
                        globals_memo[id(fn)] = declared
                    if t.id in declared:
                        ident = ('global', mi.sf.rel, t.id)
                if ident is None:
                    continue
                locks = tm.held_locks(mi, fn, node)
                writers.setdefault(ident, []).append(
                    (fkey, fn, node, mi, locks))
    for ident, sites in writers.items():
        fns = {s[0] for s in sites}
        if len(fns) < 2:
            continue  # single-writer attributes are uncontended
        for fkey, fn, node, mi, locks in sites:
            if fkey not in tm.thread_reachable:
                continue
            others = [s for s in sites if s[0] != fkey]
            other_locks: Set[Tuple] = set()
            for o in others:
                other_locks |= o[4]
            if locks & other_locks:
                continue
            attr = ident[-1]
            where = 'self.' + attr if ident[0] == 'attr' else attr
            held = ', '.join(sorted(_lock_name(x) for x in
                                    other_locks)) or 'none proven'
            yield mi.sf.finding(
                'KTPU601', node,
                f'`{where}` is written in thread-reachable '
                f'`{fn.name}` without a lock shared with its other '
                f'writer(s) (their locks: {held}) — take the same '
                f'lock, or make this the single writer')
            break  # one finding per (attribute, function)


# -- KTPU602: thread into span-recording code without re-install -------------

def _fn_calls_any(g: JitGraph, mi: ModuleInfo, fn: ast.AST,
                  names: Set[str]) -> bool:
    for node in g.scope_nodes(mi, fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in names:
            return True
        if isinstance(f, ast.Attribute) and f.attr in names:
            return True
    return False


@register('KTPU602', 'thread target reaches stage()/span-recording '
                     'code without a ScanCapture/install_span '
                     're-install on its path')
def _check_thread_span_install(ctx: Context) -> Iterable[Finding]:
    tm = thread_model(ctx)
    g = tm.graph
    stage_memo: Dict[FuncKey, bool] = {}
    install_memo: Dict[FuncKey, bool] = {}
    info_by_key: Dict[FuncKey, Tuple[ModuleInfo, ast.AST]] = {}
    for mi in g.modules.values():
        for defs in mi.defs.values():
            for fn in defs:
                info_by_key[(mi.sf.rel, fn.lineno)] = (mi, fn)
    seen_sites: Set[Tuple[str, int]] = set()
    for i, (_rk, tmi, tfn, call, site_sf) in enumerate(tm.roots):
        reach = tm._root_reach[i]
        stage_hit = None
        installed = False
        for key in reach:
            pair = info_by_key.get(key)
            if pair is None:
                continue
            if key not in stage_memo:
                stage_memo[key] = _fn_calls_any(g, pair[0], pair[1],
                                                _STAGE_CALLS)
            if key not in install_memo:
                install_memo[key] = _fn_calls_any(g, pair[0], pair[1],
                                                  _INSTALL_CALLS)
            if stage_memo[key] and stage_hit is None:
                stage_hit = pair
            if install_memo[key]:
                installed = True
                break
        if stage_hit is None or installed:
            continue
        site = (site_sf.rel, call.lineno)
        if site in seen_sites:
            continue
        seen_sites.add(site)
        smi, sfn = stage_hit
        yield site_sf.finding(
            'KTPU602', call,
            f'thread target `{tfn.name}` reaches span-recording '
            f'`{sfn.name}` ({smi.sf.rel}) but never re-installs '
            f'telemetry — wrap the worker body in '
            f'`devtel.install_capture(...)` / '
            f'`tracing.install_span(...)` so stage spans land on the '
            f'request trace')


# -- KTPU603: residency gauge without reset-on-close -------------------------

def _resolve_metric_name(g: JitGraph, mi: ModuleInfo,
                         arg: ast.AST) -> Optional[str]:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.Name):
        return _module_str_constant(g, mi, arg.id)
    if isinstance(arg, ast.Attribute) and \
            isinstance(arg.value, ast.Name):
        imp = mi.imports.get(arg.value.id)
        if imp is not None:
            dotted = imp[1] if imp[0] == 'module' \
                else f'{imp[1]}.{imp[2]}'
            tgt = g.by_dotted.get(dotted)
            if tgt is not None:
                return _module_str_constant(g, tgt, arg.attr)
    return None


def _module_str_constant(g: JitGraph, mi: ModuleInfo,
                         name: str) -> Optional[str]:
    for node in mi.sf.nodes_of(ast.Assign):
        if not isinstance(mi.parents.get(node), ast.Module):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == name and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                return node.value.value
    imp = mi.imports.get(name)
    if imp is not None and imp[0] == 'from':
        tgt = g.by_dotted.get(imp[1])
        if tgt is not None and tgt is not mi:
            return _module_str_constant(g, tgt, imp[2])
    return None


def _inside_loop(mi: ModuleInfo, node: ast.AST) -> bool:
    cur = mi.parents.get(node)
    while cur is not None and not isinstance(cur, _FUNC_DEFS):
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        cur = mi.parents.get(cur)
    return False


@register('KTPU603', 'residency-pattern gauge (set from a loop or '
                     'worker thread) registered without '
                     'mark_reset_on_close')
def _check_residency_gauge(ctx: Context) -> Iterable[Finding]:
    tm = thread_model(ctx)
    g = tm.graph
    marked: Set[str] = set()
    cleared: Set[str] = set()
    for mi in g.modules.values():
        for node in mi.sf.nodes_of(ast.Call):
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if attr not in ('mark_reset_on_close', 'clear_gauge'):
                continue
            if not node.args:
                continue
            name = _resolve_metric_name(g, mi, node.args[0])
            if name is None:
                continue
            (marked if attr == 'mark_reset_on_close'
             else cleared).add(name)
    for mi in g.modules.values():
        for node in mi.sf.nodes_of(ast.Call):
            f = node.func
            if not (isinstance(f, ast.Attribute) and
                    f.attr == 'set_gauge' and node.args):
                continue
            fn = _enclosing_function(mi, node)
            if fn is None:
                continue
            residency = _inside_loop(mi, node) or \
                (mi.sf.rel, fn.lineno) in tm.thread_reachable
            if not residency:
                continue
            name = _resolve_metric_name(g, mi, node.args[0])
            if name is None or name in marked or name in cleared:
                continue
            how = 'inside a loop' if _inside_loop(mi, node) \
                else 'from a thread-reachable worker'
            yield mi.sf.finding(
                'KTPU603', node,
                f'gauge {name!r} is set {how} in `{fn.name}` but '
                f'never marked reset-on-close — a drained server '
                f'exports the last sample forever; call '
                f'`registry.mark_reset_on_close({name!r})` at '
                f'registration (or retract with `clear_gauge`)')


# -- KTPU604: lock acquisition-order inversion --------------------------------

@register('KTPU604', 'lock acquisition-order inversion across a '
                     'two-lock pair the binder can prove')
def _check_lock_order(ctx: Context) -> Iterable[Finding]:
    tm = thread_model(ctx)
    g = tm.graph
    # ordered pair -> first (sf, node) observed acquiring that order
    orders: Dict[Tuple[LockId, LockId], Tuple] = {}

    def record(outer: LockId, inner: LockId, sf, node) -> None:
        if outer != inner:
            orders.setdefault((outer, inner), (sf, node))

    for mi in g.modules.values():
        for defs in mi.defs.values():
            for fn in defs:
                withs = tm.fn_lock_withs(mi, fn)
                if not withs:
                    continue
                for node, ids in withs:
                    # multi-item `with A, B:` acquires in order
                    for i in range(len(ids)):
                        for j in range(i + 1, len(ids)):
                            record(ids[i], ids[j], mi.sf, node)
                    held = tm.held_locks(mi, fn, node)
                    for outer in held:
                        for inner in ids:
                            record(outer, inner, mi.sf, node)
                    # one call edge deep: body calls into a function
                    # that takes its own provable lock
                    for sub in ast.walk(node):
                        if not isinstance(sub, ast.Call):
                            continue
                        for tmi, d in g.resolve_call(mi, fn, sub):
                            for _n2, ids2 in tm.fn_lock_withs(tmi, d):
                                for inner in ids2:
                                    for outer in ids:
                                        record(outer, inner,
                                               mi.sf, sub)
    reported: Set[frozenset] = set()
    for (a, b), (sf, node) in sorted(
            orders.items(), key=lambda kv: (kv[1][0].rel,
                                            kv[1][1].lineno)):
        if (b, a) not in orders:
            continue
        pair = frozenset((a, b))
        if pair in reported:
            continue
        reported.add(pair)
        other_sf, other_node = orders[(b, a)]
        yield sf.finding(
            'KTPU604', node,
            f'lock order inversion: `{_lock_name(a)}` then '
            f'`{_lock_name(b)}` here, but `{_lock_name(b)}` then '
            f'`{_lock_name(a)}` at {other_sf.rel}:{other_node.lineno} '
            f'— pick one global order or merge the critical sections')
