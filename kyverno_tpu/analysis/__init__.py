"""ktpu-lint: AST-based static analysis for fast-path trace-safety,
retrace hazards, and taxonomy/catalog drift.

The failure modes that silently break "compiled once, served from TPU,
bit-identical output" are program-structure properties, not runtime
bugs: a host sync inside a jit'd region, a retrace storm from an
unhashable closure, a fallback site that drifted out of the coverage
taxonomy.  pytest never sees them; this package enforces them on every
commit (``scripts/analyze.py``, wired into tier-1 by
``tests/test_static_analysis.py``).

Layout:

* :mod:`.core` — finding model, rule registry (stable ``KTPU###`` ids),
  per-line ``# ktpu: noqa[RULEID] -- reason`` suppressions, committed
  baseline for grandfathered findings, and the :class:`Analyzer` driver
* :mod:`.jitgraph` — import/def indexing and the jit-entry call graph
  shared by the trace-safety and retrace passes
* :mod:`.trace_safety` — KTPU101/102/103 (host syncs inside jit regions)
* :mod:`.retrace` — KTPU201/202/203 (retrace hazards)
* :mod:`.taxonomy` — KTPU301/302/303 (fallback-reason taxonomy drift)
* :mod:`.envreg` — KTPU401/402 (``KTPU_*`` knob registry drift)
* :mod:`.catalog_pass` — KTPU501/502/503 (metric catalog drift; the
  framework home of ``scripts/check_metric_names.py``),
  KTPU504/505 (span-name catalog drift against
  ``observability/catalog.py:SPANS``), and KTPU506 (unit mismatch:
  ``*_seconds``/``*_bytes`` metrics fed ms or str-length values)
* :mod:`.knobs` — the machine-readable ``KTPU_*`` knob registry that
  drives both KTPU401/402 and the README knob table
"""

from .core import (Analyzer, Finding, Rule, RULES, load_baseline,  # noqa: F401
                   write_baseline)

# importing the pass modules registers their rules
from . import trace_safety  # noqa: F401,E402
from . import retrace  # noqa: F401,E402
from . import taxonomy  # noqa: F401,E402
from . import envreg  # noqa: F401,E402
from . import catalog_pass  # noqa: F401,E402
from . import concurrency  # noqa: F401,E402
