"""Trace-safety passes (KTPU1xx): host syncs inside jit regions.

A host sync inside a jitted region either crashes the trace
(``TracerArrayConversionError``) or — worse — silently forces a
device→host readback per call and caps the pipeline at PCIe/tunnel
latency.  These passes flag the constructs on any function reachable
from the ``jax.jit`` / ``pjit`` sites in the tree (``ops/eval.py``,
``parallel/mesh.py``, and whatever future modules grow jit entries).

Since the v2 engine these passes are **interprocedural**: KTPU102/103
consult the param-rooted taint lattice, so a helper three call edges
below the entry that casts or branches on a value derived from a
traced *argument* is a finding at the helper's own site, with the
entry→helper call chain in the message.  Purely local evidence (a
``jnp.*`` call in the expression, a local assigned from one) still
counts exactly as before.

* **KTPU101** — explicit host-sync calls: ``.item()``, ``.tolist()``,
  ``.block_until_ready()``, ``np.asarray`` / ``np.array`` /
  ``jax.device_get`` on anything jit-reachable.
* **KTPU102** — Python scalar casts (``float`` / ``int`` / ``bool``)
  over a traced expression: one whose subtree calls into ``jnp`` /
  ``jax``, or a local assigned from such a call, or a
  **tracer-tainted parameter** (static jit args excluded).
* **KTPU103** — Python ``if`` / ``while`` control flow on a traced
  expression (``is None`` identity tests excluded — those gate
  Python-level optionality, not array values).
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from .core import Context, Finding, register
from .jitgraph import jit_graph

#: attribute calls that force a device→host transfer wherever they run
SYNC_METHODS = {'item', 'tolist', 'block_until_ready'}

#: ``module.func`` spellings that materialize a host array
SYNC_MODULE_CALLS = {
    ('np', 'asarray'), ('np', 'array'), ('numpy', 'asarray'),
    ('numpy', 'array'), ('jax', 'device_get'),
}

#: roots whose attribute-calls produce traced values
_TRACED_ROOTS = {'jnp', 'jax'}


def _attr_root(node: ast.AST):
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _traced_names(fn: ast.AST) -> Set[str]:
    """Names assigned (anywhere in ``fn``) from a ``jnp.*``/``jax.*``
    call — the local-evidence layer under the interprocedural taint."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = node.value
            if value is None or not _contains_traced_call(value, set()):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _contains_traced_call(expr: ast.AST, traced_names: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            root = _attr_root(node.func)
            if root in _TRACED_ROOTS:
                return True
        elif isinstance(node, ast.Name) and node.id in traced_names:
            return True
    return False


def _is_none_test(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` (possibly under ``not``)."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_none_test(test.operand)
    if isinstance(test, ast.Compare):
        return any(isinstance(op, (ast.Is, ast.IsNot))
                   for op in test.ops)
    return False


def _chain_suffix(graph, mi, fn) -> str:
    chain = graph.chain_for(mi, fn)
    return f' (call chain: {chain})' if chain else ''


@register('KTPU101', 'host-sync call (.item()/.tolist()/'
                     '.block_until_ready()/np.asarray/jax.device_get) '
                     'inside a jit-reachable function')
def _check_host_sync(ctx: Context) -> Iterable[Finding]:
    graph = jit_graph(ctx)
    for sf, mi, fn in graph.reachable_functions():
        for node in graph.scope_nodes(mi, fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr in SYNC_METHODS and not node.args:
                    yield sf.finding(
                        'KTPU101', node,
                        f'`.{f.attr}()` forces a device sync inside '
                        f'jit-reachable `{fn.name}` — keep the value '
                        f'on device or hoist to the host side'
                        f'{_chain_suffix(graph, mi, fn)}')
                    continue
                base = f.value
                if isinstance(base, ast.Name) and \
                        (base.id, f.attr) in SYNC_MODULE_CALLS:
                    yield sf.finding(
                        'KTPU101', node,
                        f'`{base.id}.{f.attr}` materializes a host '
                        f'array inside jit-reachable `{fn.name}` — '
                        f'use jnp, or move the conversion outside the '
                        f'traced region'
                        f'{_chain_suffix(graph, mi, fn)}')


@register('KTPU102', 'Python scalar cast (float/int/bool) over a '
                     'traced or tracer-tainted expression inside a '
                     'jit-reachable function')
def _check_scalar_cast(ctx: Context) -> Iterable[Finding]:
    graph = jit_graph(ctx)
    for sf, mi, fn in graph.reachable_functions():
        traced = _traced_names(fn)
        tainted = graph.tainted_names_for(mi, fn)
        for node in graph.scope_nodes(mi, fn):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Name) and
                    node.func.id in ('float', 'int', 'bool') and
                    len(node.args) == 1):
                continue
            arg = node.args[0]
            local_hit = _contains_traced_call(arg, traced)
            taint_hit = bool(tainted) and \
                graph.expr_tainted(mi, fn, arg, tainted)
            if local_hit or taint_hit:
                why = 'a traced expression' if local_hit else \
                    'a tracer-tainted argument'
                yield sf.finding(
                    'KTPU102',
                    node,
                    f'`{node.func.id}(...)` over {why} '
                    f'in jit-reachable `{fn.name}` leaks the tracer '
                    f'to the host — keep it as a jnp array'
                    f'{_chain_suffix(graph, mi, fn)}')


@register('KTPU103', 'Python if/while branching on a traced or '
                     'tracer-tainted expression inside a '
                     'jit-reachable function')
def _check_tracer_branch(ctx: Context) -> Iterable[Finding]:
    graph = jit_graph(ctx)
    for sf, mi, fn in graph.reachable_functions():
        traced = _traced_names(fn)
        tainted = graph.tainted_names_for(mi, fn)
        for node in graph.scope_nodes(mi, fn):
            if not isinstance(node, (ast.If, ast.While)) or \
                    _is_none_test(node.test):
                continue
            local_hit = _contains_traced_call(node.test, traced)
            taint_hit = bool(tainted) and \
                graph.expr_tainted(mi, fn, node.test, tainted)
            if local_hit or taint_hit:
                kw = 'if' if isinstance(node, ast.If) else 'while'
                yield sf.finding(
                    'KTPU103', node,
                    f'Python `{kw}` on a traced expression in '
                    f'jit-reachable `{fn.name}` — the branch '
                    f'concretizes the tracer; use jnp.where / lax.cond'
                    f'{_chain_suffix(graph, mi, fn)}')
