"""Retrace-hazard passes (KTPU2xx).

``jax.jit`` caches compiled executables on (input avals × static
args × closure constants captured at trace time).  Three program
shapes defeat that cache silently:

* **KTPU201** — a jit-wrapped function *reads* a mutable container
  (list/dict/set) bound at module or enclosing-function scope.  The
  trace bakes in whatever the container held at trace time; later
  mutations are invisible to the compiled executable (stale results),
  and "fixing" that by retracing per call is a retrace storm.
* **KTPU202** — ``static_argnums`` / ``static_argnames`` pointing at a
  parameter whose default is an unhashable container: the first call
  with the default raises ``TypeError: unhashable``, and call sites
  passing fresh literals retrace on every call (equality-hashed cache
  keys never hit).
* **KTPU203** — Python ``if`` / ``while`` on ``.shape`` / ``.ndim``
  inside a jit-reachable function: legal (shapes are trace-static) but
  every distinct shape takes a different branch → one executable per
  shape.  Intentional shape-bucketing gets a ``# ktpu: noqa[KTPU203]``
  with the reason; accidental shape branching gets rewritten.
* **KTPU204** — a batch-encode entry (``encode_batch`` /
  ``encode_mutate_batch``) whose ``padded_n`` is computed instead of
  drawn from the canonical shape table (``compiler/shapes.py``): magic
  row counts and ``1 << n.bit_length()`` ladders each mint a fresh XLA
  shape, silently regrowing the per-bucket executable zoo the ragged
  kernels retired.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from .core import Context, Finding, register
from .jitgraph import jit_graph, walk_scope

_MUTABLE_CTORS = {'list', 'dict', 'set', 'defaultdict', 'OrderedDict',
                  'deque', 'Counter'}


def _is_mutable_container(value: Optional[ast.AST]) -> bool:
    if value is None:
        return False
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.SetComp, ast.DictComp)):
        return True
    if isinstance(value, ast.Call):
        f = value.func
        name = f.id if isinstance(f, ast.Name) else \
            f.attr if isinstance(f, ast.Attribute) else None
        return name in _MUTABLE_CTORS
    return False


def _scope_bindings(scope: ast.AST) -> dict:
    """name → last top-level assignment value in ``scope`` (direct
    statements only; nested function bodies are their own scopes)."""
    out = {}
    body = getattr(scope, 'body', [])
    stack = list(body)
    while stack:
        node = stack.pop(0)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.value is not None:
            out[node.target.id] = node.value
        elif isinstance(node, (ast.If, ast.For, ast.While, ast.With,
                               ast.Try)):
            for attr in ('body', 'orelse', 'finalbody'):
                stack.extend(getattr(node, attr, []) or [])
            for h in getattr(node, 'handlers', []) or []:
                stack.extend(h.body)
    return out


def _local_names(fn: ast.AST) -> set:
    names = {a.arg for a in fn.args.args + fn.args.kwonlyargs +
             getattr(fn.args, 'posonlyargs', [])}
    if fn.args.vararg:
        names.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        names.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        names.add(leaf.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            t = node.target
            for leaf in ast.walk(t):
                if isinstance(leaf, ast.Name):
                    names.add(leaf.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not fn:
            names.add(node.name)
    return names


@register('KTPU201', 'jit-wrapped function reads a mutable module-'
                     'global or enclosing-scope container (trace bakes '
                     'in stale state / retrace storm)')
def _check_mutable_closure(ctx: Context) -> Iterable[Finding]:
    graph = jit_graph(ctx)
    seen = set()
    for mi, fn, _site in graph.entries:
        key = (mi.sf.rel, fn.lineno)
        if key in seen:
            continue
        seen.add(key)
        local = _local_names(fn)
        scopes = graph.enclosing_scopes(mi, fn)
        bindings = {}
        # outermost (module) first so inner scopes shadow outer ones
        for scope in reversed(scopes):
            bindings.update(_scope_bindings(scope))
        flagged = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name) and
                    isinstance(node.ctx, ast.Load)):
                continue
            name = node.id
            if name in local or name in flagged:
                continue
            if _is_mutable_container(bindings.get(name)):
                flagged.add(name)
                yield mi.sf.finding(
                    'KTPU201', node,
                    f'jit-wrapped `{fn.name}` reads mutable container '
                    f'`{name}` from an enclosing scope — the trace '
                    f'captures its trace-time contents; freeze it '
                    f'(tuple) or pass it as an argument')
        # `self.X` closure reads: a jitted *method* closes over its
        # instance, so a mutable-container attribute is exactly the
        # module-global hazard above — the binder's per-class
        # `self.X = ...` sites tell us which attrs are containers
        fi = mi.func_info.get(fn)
        ci = mi.classes.get(fi.cls) if fi is not None and \
            fi.cls is not None else None
        if ci is not None:
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Attribute) and
                        isinstance(node.ctx, ast.Load) and
                        isinstance(node.value, ast.Name) and
                        node.value.id == 'self'):
                    continue
                attr = node.attr
                if attr in flagged:
                    continue
                if _is_mutable_container(ci.attr_values.get(attr)):
                    flagged.add(attr)
                    yield mi.sf.finding(
                        'KTPU201', node,
                        f'jit-wrapped method `{fn.name}` reads mutable '
                        f'container `self.{attr}` — the trace captures '
                        f'its trace-time contents; freeze it (tuple) '
                        f'or pass it as an argument')


def _static_params(call: ast.Call, fn: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(param name, default) pairs selected by static_argnums/names."""
    args = fn.args
    params = getattr(args, 'posonlyargs', []) + args.args
    defaults: dict = {}
    if args.defaults:
        for p, d in zip(params[-len(args.defaults):], args.defaults):
            defaults[p.arg] = d
    for p, d in zip(args.kwonlyargs, args.kw_defaults or []):
        if d is not None:
            defaults[p.arg] = d
    selected: List[str] = []
    for kw in call.keywords:
        if kw.arg == 'static_argnums':
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, int) and \
                        v.value < len(params):
                    selected.append(params[v.value].arg)
        elif kw.arg == 'static_argnames':
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    selected.append(v.value)
    return [(n, defaults[n]) for n in selected if n in defaults]


@register('KTPU202', 'static jit argument with an unhashable '
                     '(mutable-container) default — cache keys cannot '
                     'hash, calls retrace or raise')
def _check_static_args(ctx: Context) -> Iterable[Finding]:
    graph = jit_graph(ctx)
    for mi, fn, site in graph.entries:
        if not isinstance(site, ast.Call):
            continue
        for name, default in _static_params(site, fn):
            if _is_mutable_container(default):
                yield mi.sf.finding(
                    'KTPU202', site,
                    f'static arg `{name}` of jit-wrapped `{fn.name}` '
                    f'defaults to an unhashable container — use a '
                    f'tuple/frozenset or drop it from static_arg*')


def _mentions_shape(test: ast.AST) -> Optional[str]:
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and \
                node.attr in ('shape', 'ndim'):
            return node.attr
    return None


@register('KTPU203', 'shape-dependent Python branching inside a '
                     'jit-reachable function (one executable per '
                     'distinct shape)')
def _check_shape_branch(ctx: Context) -> Iterable[Finding]:
    graph = jit_graph(ctx)
    for sf, _mi, fn in graph.reachable_functions():
        for node in walk_scope(fn):
            if isinstance(node, (ast.If, ast.While)):
                attr = _mentions_shape(node.test)
                if attr is not None:
                    kw = 'if' if isinstance(node, ast.If) else 'while'
                    yield sf.finding(
                        'KTPU203', node,
                        f'`{kw}` on `.{attr}` in jit-reachable '
                        f'`{fn.name}` retraces per distinct shape — '
                        f'bucket shapes deliberately (and noqa with '
                        f'the reason) or make the code rank-generic')


#: batch-encode entry points whose row padding decides a compiled shape
_ENCODE_ENTRIES = frozenset({'encode_batch', 'encode_mutate_batch'})
#: provenance that marks a padded_n as canonical-table-derived
_CANONICAL_FNS = frozenset({'canonical_capacity', 'canonical_caps',
                            'small_capacity', 'pad_to_multiple'})


def _callee_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _padded_n_arg(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == 'padded_n':
            return kw.value
    # encode_batch(resources, cps, padded_n, ...) /
    # encode_mutate_batch(resources, program, padded_n, ...)
    if len(call.args) >= 3:
        return call.args[2]
    return None


def _contains_canonical_call(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and
               _callee_name(n.func) in _CANONICAL_FNS
               for n in ast.walk(expr))


def _looks_computed(expr: ast.AST) -> bool:
    """True for the bucket-ladder shapes: bit_length()/shift
    arithmetic, or a hard-coded nonzero row count."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and _callee_name(n.func) == \
                'bit_length':
            return True
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.LShift):
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, int) and \
                not isinstance(n.value, bool) and n.value != 0:
            return True
    return False


#: functions whose bodies (plus their one-level same-file callees) ARE
#: the streaming encode hot path — per-row allocations here run a
#: million times per scan
_HOT_ENTRIES = frozenset({'encode_batch', 'encode_mutate_batch'})
#: call names that materialize per-row garbage: dict() construction,
#: deep copies, JSON serialization
_PER_ROW_ALLOC_CALLS = frozenset({'deepcopy', 'dumps', 'dict'})
#: comprehension nodes: their element expressions run once per
#: iteration, exactly like a loop body
_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                   ast.DictComp)


def _flag_hot_loop_allocs(sf, fn: ast.AST) -> Iterable[Finding]:
    found: List[ast.AST] = []

    def visit(node: ast.AST, in_loop: bool) -> None:
        if in_loop:
            if isinstance(node, (ast.Dict, ast.DictComp)):
                found.append(node)
            elif isinstance(node, ast.Call) and \
                    _callee_name(node.func) in _PER_ROW_ALLOC_CALLS:
                found.append(node)
        inner = in_loop or isinstance(
            node, (ast.For, ast.AsyncFor, ast.While) + _COMPREHENSIONS)
        for child in ast.iter_child_nodes(node):
            visit(child, inner)

    visit(fn, False)
    lines_seen: set = set()
    for node in found:
        if node.lineno in lines_seen:
            continue  # one finding per line, however many dicts it holds
        lines_seen.add(node.lineno)
        what = 'dict construction' if isinstance(
            node, (ast.Dict, ast.DictComp)) else \
            f'`{_callee_name(node.func)}(...)`'
        yield sf.finding(
            'KTPU205', node,
            f'per-row {what} inside `{fn.name}` on the streaming '
            f'encode hot path — hoist it out of the loop, reuse a '
            f'shared buffer/context, or go columnar '
            f'(encode.Lanes.encode_column)')


@register('KTPU205', 'per-row dict/deepcopy/json.dumps construction in '
                     'a function reachable from the streaming encode '
                     'hot path (encode_batch/encode_mutate_batch + '
                     'one-level callees) — allocations here run once '
                     'per resource per chunk')
def _check_hot_path_allocs(ctx: Context) -> Iterable[Finding]:
    for sf in ctx.files:
        if sf.tree is None:
            continue
        defs: dict = {}
        for node in sf.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        entries = [defs[n] for n in sorted(_HOT_ENTRIES) if n in defs]
        if not entries:
            continue
        # the hot set: the encode entries plus every same-file function
        # they call directly (bare-name resolution, one level — the
        # same local-dataflow depth as KTPU204)
        hot: List[ast.AST] = []
        seen: set = set()
        for fn in entries:
            if id(fn) not in seen:
                seen.add(id(fn))
                hot.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    target = defs.get(_callee_name(node.func))
                    if target is not None and id(target) not in seen:
                        seen.add(id(target))
                        hot.append(target)
        for fn in hot:
            yield from _flag_hot_loop_allocs(sf, fn)


@register('KTPU204', 'batch-encode padded_n not drawn from the '
                     'canonical shape table (compiler/shapes.py) — '
                     'each computed row count mints a fresh XLA '
                     'executable (the bucket zoo regrows)')
def _check_canonical_padding(ctx: Context) -> Iterable[Finding]:
    for sf in ctx.files:
        if sf.tree is None:
            continue
        # innermost enclosing scope per call site, for one-level
        # name resolution of `padded_n=<name>` (same local-dataflow
        # depth as the KTPU1xx taint passes)
        scopes: List[Tuple[ast.AST, ast.Call]] = []

        def visit(node: ast.AST, scope: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                inner = scope
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    inner = child
                if isinstance(child, ast.Call) and \
                        _callee_name(child.func) in _ENCODE_ENTRIES:
                    scopes.append((scope, child))
                visit(child, inner)

        visit(sf.tree, sf.tree)
        for scope, call in scopes:
            expr = _padded_n_arg(call)
            if expr is None:
                continue
            if isinstance(expr, ast.Name):
                resolved = _scope_bindings(scope).get(expr.id)
                if resolved is None:
                    continue  # parameter / out-of-scope: undecidable
                expr = resolved
            if _contains_canonical_call(expr):
                continue
            if _looks_computed(expr):
                entry = _callee_name(call.func)
                yield sf.finding(
                    'KTPU204', call,
                    f'`{entry}` padded_n is computed locally — draw '
                    f'it from the canonical shape table '
                    f'(compiler/shapes.canonical_capacity) so XLA '
                    f'only ever compiles the canonical row shapes')
