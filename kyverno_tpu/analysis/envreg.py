"""Env-knob registry passes (KTPU4xx).

Every ``KTPU_*`` environment read must appear in the
:mod:`kyverno_tpu.analysis.knobs` registry (which also generates the
README knob table), and every registry entry must still have a read
site.  Detection covers the spellings this tree actually uses:
``os.environ.get(...)``, ``os.environ[...]``, ``os.getenv(...)``, and
the import-dodging ``__import__('os').environ.get(...)`` /
``_os.environ.get(...)`` forms (any root object with an ``environ``
attribute counts).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from .core import Context, Finding, SourceFile, register
from .knobs import KNOBS

PREFIX = 'KTPU_'


def _env_read_name(node: ast.AST):
    """The literal env-var name read by ``node``, if it is an environ
    access of any spelling."""
    # os.environ['X'] (including .get-less Subscript)
    if isinstance(node, ast.Subscript) and \
            isinstance(node.value, ast.Attribute) and \
            node.value.attr == 'environ':
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return sl.value
        return None
    if not isinstance(node, ast.Call) or not node.args:
        return None
    f = node.func
    key = node.args[0]
    if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
        return None
    if isinstance(f, ast.Attribute):
        if f.attr == 'get' and isinstance(f.value, ast.Attribute) and \
                f.value.attr == 'environ':
            return key.value
        if f.attr == 'getenv':
            return key.value
    elif isinstance(f, ast.Name) and f.id == 'getenv':
        return key.value
    return None


def env_reads(ctx: Context) -> List[Tuple[SourceFile, ast.AST, str]]:
    def build():
        out = []
        for sf in ctx.files:
            if sf.tree is None:
                continue
            for node in sf.walk():
                name = _env_read_name(node)
                if name is not None and name.startswith(PREFIX):
                    out.append((sf, node, name))
        return out
    return ctx.cached('env_reads', build)


@register('KTPU401', 'KTPU_* environ read missing from the knob '
                     'registry (analysis/knobs.py)')
def _check_unregistered_reads(ctx: Context) -> Iterable[Finding]:
    for sf, node, name in env_reads(ctx):
        if name not in KNOBS:
            yield sf.finding(
                'KTPU401', node,
                f'env knob {name!r} is not registered in '
                f'kyverno_tpu/analysis/knobs.py — register it (with '
                f'default, type, and operator-facing help) so the '
                f'README table includes it')


@register('KTPU402', 'registered knob with no read site in the tree '
                     '(dead knob)')
def _check_dead_knobs(ctx: Context) -> Iterable[Finding]:
    read = {name for _sf, _node, name in env_reads(ctx)}
    anchor = ctx.by_rel('kyverno_tpu/analysis/knobs.py')
    for name in sorted(KNOBS):
        if name not in read:
            target = anchor if anchor is not None else ctx.files[0]
            line = 1
            if anchor is not None:
                for i, text in enumerate(anchor.lines, start=1):
                    if f"'{name}'" in text:
                        line = i
                        break
            yield target.finding(
                'KTPU402', line,
                f'knob {name!r} is registered but never read — remove '
                f'the entry or wire the read site')
