"""Lint-framework core: findings, rule registry, suppressions, baseline.

Design constraints (they shape every API here):

* **Pure AST** — the analyzer must run in tier-1 on a CPU-only box in
  well under 10 seconds, so no pass may import the modules it inspects
  (the one deliberate exception is the metric catalog, a plain table).
* **Stable rule ids** — ``KTPU###`` strings are a public contract:
  they appear in ``# ktpu: noqa[...]`` comments and in the committed
  baseline, so renumbering a rule invalidates user annotations.
* **Suppressions carry reasons** — ``# ktpu: noqa[KTPU101] -- why`` is
  the only accepted form; a bare ``noqa[...]`` is itself a finding
  (KTPU001), and a noqa that suppresses nothing is one too (KTPU002),
  so annotations can never silently rot.
* **Baseline is minimal by construction** — entries match on (rule,
  path, stripped line text) so they survive line drift but die with
  the code they grandfathered; a stale entry fails ``--strict``.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

#: suppression comment — a hash, then ``ktpu: noqa[RULE,...]``,
#: optionally followed by ``-- reason text`` (reason required: a bare
#: directive is itself a KTPU001 finding)
NOQA_RE = re.compile(
    r'#\s*ktpu:\s*noqa\[([A-Za-z0-9_,\s]*)\]\s*(?:--\s*(\S.*))?')

RULE_ID_RE = re.compile(r'^KTPU\d{3}$')

DEFAULT_BASELINE = '.ktpu-baseline.json'


@dataclass(frozen=True)
class Finding:
    rule_id: str
    path: str          # repo-relative
    line: int          # 1-indexed
    message: str
    line_text: str = ''  # stripped source line, the baseline match key

    def key(self) -> Tuple[str, str, str]:
        return (self.rule_id, self.path, self.line_text)

    def render(self) -> str:
        return f'{self.path}:{self.line}: {self.rule_id} {self.message}'


@dataclass
class Noqa:
    line: int
    rule_ids: Tuple[str, ...]
    reason: str
    used: bool = False


class SourceFile:
    """One parsed source file: AST + per-line noqa directives.

    Also the per-file AST memo: :meth:`walk` flattens the tree once
    and :meth:`nodes_of` indexes it by node type once, so a dozen
    passes asking "every Call in this file" cost one traversal total
    instead of one ``ast.walk`` each — the difference between the
    analyzer fitting its 10s tier-1 budget and not.
    """

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[str] = None
        self._nodes: Optional[List[ast.AST]] = None
        self._by_type: Dict[type, List[ast.AST]] = {}
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.syntax_error = str(e)
        # tokenize so only real comments count — a docstring QUOTING a
        # `# ktpu: noqa[...]` directive must not suppress anything
        self.noqa: Dict[int, Noqa] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = NOQA_RE.search(tok.string)
                if m:
                    i = tok.start[0]
                    ids = tuple(x.strip() for x in m.group(1).split(',')
                                if x.strip())
                    self.noqa[i] = Noqa(i, ids,
                                        (m.group(2) or '').strip())
        except (tokenize.TokenError, IndentationError):
            pass  # syntax_error already recorded above

    def walk(self) -> List[ast.AST]:
        """Every node in the file, flattened once and memoized."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree)) \
                if self.tree is not None else []
        return self._nodes

    def nodes_of(self, *types: type) -> List[ast.AST]:
        """Every node of the given type(s), from a memoized per-type
        index (``isinstance``-exact: pass each concrete type)."""
        out: List[ast.AST] = []
        for t in types:
            if t not in self._by_type:
                self._by_type[t] = [n for n in self.walk()
                                    if type(n) is t]
            out.extend(self._by_type[t])
        return out

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ''

    def finding(self, rule_id: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, 'lineno', node_or_line)
        return Finding(rule_id, self.rel, line, message,
                       self.line_text(line))


@dataclass
class Rule:
    rule_id: str
    summary: str
    check: Callable[['Context'], Iterable[Finding]]
    meta: bool = False  # meta rules run after suppression filtering


#: the registry — stable ids, one entry per pass
RULES: Dict[str, Rule] = {}


def register(rule_id: str, summary: str, meta: bool = False):
    """Register a lint pass under a stable ``KTPU###`` id."""
    if not RULE_ID_RE.match(rule_id):
        raise ValueError(f'bad rule id {rule_id!r}')

    def deco(fn: Callable[['Context'], Iterable[Finding]]):
        if rule_id in RULES:
            raise ValueError(f'duplicate rule id {rule_id}')
        RULES[rule_id] = Rule(rule_id, summary, fn, meta=meta)
        return fn
    return deco


class Context:
    """Shared state handed to every pass: the parsed file set plus
    lazily-built cross-file indexes (jit call graph, taxonomy, ...)."""

    def __init__(self, files: List[SourceFile], root: str):
        self.files = files
        self.root = root
        self._cache: Dict[str, object] = {}

    def by_rel(self, rel: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.rel == rel:
                return f
        return None

    def cached(self, key: str, build: Callable[[], object]):
        if key not in self._cache:
            self._cache[key] = build()
        return self._cache[key]


# -- file collection ---------------------------------------------------------

#: the ONE directory exclude list every walker shares — the driver
#: (``scripts/analyze.py``), :func:`collect_files`, and the
#: ``catalog_pass`` shim all consume this instead of keeping private
#: copies that drift.  ``tests`` is excluded because fixture strings
#: deliberately contain violations; caches/VCS dirs never hold source.
EXCLUDE_DIRS = frozenset({
    '__pycache__', '.git', '.cache', 'node_modules', 'tests',
    'fixtures',
})

#: the default analyzed file set, shared by the driver and the
#: standalone checker shims (``scripts/`` is *included* by intent —
#: the lint tooling lints itself; ``tests/`` is excluded above)
DEFAULT_SOURCE_PATHS = ('kyverno_tpu', 'scripts', 'bench.py')


def collect_files(paths: List[str], root: str) -> List[SourceFile]:
    out: List[SourceFile] = []
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            cands = [ap]
        else:
            cands = []
            for base, dirs, names in os.walk(ap):
                dirs[:] = [d for d in dirs if d not in EXCLUDE_DIRS]
                cands.extend(os.path.join(base, n) for n in sorted(names)
                             if n.endswith('.py'))
        for c in sorted(cands):
            c = os.path.abspath(c)
            if c in seen:
                continue
            seen.add(c)
            with open(c, encoding='utf-8') as f:
                text = f.read()
            out.append(SourceFile(c, os.path.relpath(c, root), text))
    return out


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> List[dict]:
    """Entries: ``{"rule", "path", "match", "reason"}`` — ``match`` is
    the stripped source line of the grandfathered finding."""
    if not os.path.exists(path):
        return []
    with open(path, encoding='utf-8') as f:
        doc = json.load(f)
    return list(doc.get('entries', []))


def write_baseline(path: str, findings: List[Finding],
                   reason: str = 'TODO: justify this grandfathered '
                                 'finding') -> None:
    entries = []
    seen = set()
    for f in sorted(findings, key=lambda f: (f.rule_id, f.path, f.line)):
        key = f.key()
        if key in seen:
            continue
        seen.add(key)
        entries.append({'rule': f.rule_id, 'path': f.path,
                        'match': f.line_text, 'reason': reason})
    with open(path, 'w', encoding='utf-8') as fh:
        json.dump({'entries': entries}, fh, indent=2)
        fh.write('\n')


# -- meta rules (registered here so the registry always has them) ------------

@register('KTPU001', 'ktpu noqa suppression without a reason string '
                     '(`# ktpu: noqa[ID] -- why`)', meta=True)
def _check_noqa_reason(ctx: Context) -> Iterable[Finding]:
    for sf in ctx.files:
        for nq in sf.noqa.values():
            bad_ids = [i for i in nq.rule_ids if not RULE_ID_RE.match(i)]
            if bad_ids or not nq.rule_ids:
                yield sf.finding(
                    'KTPU001', nq.line,
                    f'malformed ktpu noqa rule list {nq.rule_ids!r} — '
                    f'use explicit KTPU### ids')
            elif not nq.reason:
                yield sf.finding(
                    'KTPU001', nq.line,
                    f'noqa[{",".join(nq.rule_ids)}] has no reason — '
                    f'append `-- <why this is intentionally host-side>`')


@register('KTPU002', 'ktpu noqa suppression that suppresses nothing '
                     '(stale annotation)', meta=True)
def _check_noqa_used(ctx: Context) -> Iterable[Finding]:
    for sf in ctx.files:
        for nq in sf.noqa.values():
            if not nq.used and nq.rule_ids and \
                    all(RULE_ID_RE.match(i) for i in nq.rule_ids):
                yield sf.finding(
                    'KTPU002', nq.line,
                    f'noqa[{",".join(nq.rule_ids)}] suppresses no '
                    f'finding — remove the stale annotation')


# -- driver ------------------------------------------------------------------

@dataclass
class Report:
    active: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[dict] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)

    def to_json(self) -> dict:
        def enc(fs):
            return [{'rule': f.rule_id, 'path': f.path, 'line': f.line,
                     'message': f.message, 'match': f.line_text}
                    for f in fs]
        return {'active': enc(self.active),
                'suppressed': enc(self.suppressed),
                'baselined': enc(self.baselined),
                'stale_baseline': self.stale_baseline,
                'errors': self.errors,
                'counts': {'active': len(self.active),
                           'suppressed': len(self.suppressed),
                           'baselined': len(self.baselined),
                           'stale_baseline': len(self.stale_baseline)}}


class Analyzer:
    """Run every registered pass over a file set, apply suppressions,
    then the baseline; meta passes (noqa hygiene) run after suppression
    state is known."""

    def __init__(self, paths: List[str], root: str,
                 baseline_path: Optional[str] = None,
                 rules: Optional[List[str]] = None):
        self.root = os.path.abspath(root)
        self.files = collect_files(paths, self.root)
        self.ctx = Context(self.files, self.root)
        self.baseline_path = baseline_path
        self.rule_ids = rules  # None = all

    def _selected(self, meta: bool) -> List[Rule]:
        out = []
        for rid in sorted(RULES):
            rule = RULES[rid]
            if rule.meta != meta:
                continue
            if self.rule_ids is not None and rid not in self.rule_ids:
                continue
            out.append(rule)
        return out

    def _suppressed_by(self, sf: SourceFile, f: Finding) -> Optional[Noqa]:
        # a directive suppresses findings on its own line, or — for
        # statements that cannot carry a trailing comment — anywhere in
        # the contiguous comment block directly above (so wrapped
        # reason text keeps working)
        nq = sf.noqa.get(f.line)
        if nq is not None and f.rule_id in nq.rule_ids:
            return nq
        line = f.line - 1
        while line > 0 and sf.line_text(line).startswith('#'):
            nq = sf.noqa.get(line)
            if nq is not None and f.rule_id in nq.rule_ids:
                return nq
            line -= 1
        return None

    def run(self) -> Report:
        rep = Report()
        for sf in self.files:
            if sf.syntax_error:
                rep.errors.append(f'{sf.rel}: syntax error: '
                                  f'{sf.syntax_error}')
        by_rel = {sf.rel: sf for sf in self.files}
        raw: List[Finding] = []
        for rule in self._selected(meta=False):
            raw.extend(rule.check(self.ctx))
        kept: List[Finding] = []
        for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule_id)):
            sf = by_rel.get(f.path)
            nq = self._suppressed_by(sf, f) if sf is not None else None
            if nq is not None:
                nq.used = True
                rep.suppressed.append(f)
            else:
                kept.append(f)
        # meta passes see final suppression usage; they are not
        # themselves noqa-suppressible (that would be circular) but may
        # be baselined
        for rule in self._selected(meta=True):
            kept.extend(rule.check(self.ctx))
        entries = load_baseline(self.baseline_path) \
            if self.baseline_path else []
        matched = [0] * len(entries)
        for f in kept:
            hit = None
            for i, e in enumerate(entries):
                if (e.get('rule'), e.get('path'), e.get('match')) == \
                        f.key():
                    hit = i
                    break
            if hit is None:
                rep.active.append(f)
            else:
                matched[hit] += 1
                rep.baselined.append(f)
        for i, e in enumerate(entries):
            if not matched[i]:
                rep.stale_baseline.append(e)
            if not str(e.get('reason', '')).strip() or \
                    str(e.get('reason', '')).startswith('TODO'):
                rep.errors.append(
                    f'baseline entry {e.get("rule")} {e.get("path")} '
                    f'has no justification — every grandfathered '
                    f'finding needs a reason')
        rep.active.sort(key=lambda f: (f.path, f.line, f.rule_id))
        return rep
