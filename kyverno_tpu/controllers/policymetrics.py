"""Policy metrics controller.

Mirrors the reference's informer-driven policy metrics (reference:
pkg/controllers/metrics/policy/controller.go:155 — policy change
counters and per-rule info gauges emitted from policy add/update/delete
events).  The dynamic client's watch feed is the informer equivalent:
every Policy/ClusterPolicy event increments
``kyverno_policy_changes_total`` and re-derives the
``kyverno_policy_rule_info_total`` gauge set (1 per live rule).
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from ..api.policy import Policy
from ..observability.metrics import POLICY_CHANGES, MetricsRegistry

POLICY_RULE_INFO = 'kyverno_policy_rule_info_total'

_POLICY_KINDS = {'ClusterPolicy', 'Policy'}


class PolicyMetricsController:
    """reference: pkg/controllers/metrics/policy/controller.go"""

    def __init__(self, client, registry: MetricsRegistry):
        self.client = client
        self.registry = registry
        self._lock = threading.Lock()
        # (policy key) → {rule label-tuples} for gauge retraction
        self._rules: Dict[str, set] = {}
        client.watch(self._on_event)
        # informers replay ADDED for objects that exist before the watch
        # starts (controller.go informer cache sync) — list and seed the
        # rule-info gauges so a restart doesn't zero the series
        for api_version, kind in (('kyverno.io/v1', 'ClusterPolicy'),
                                  ('kyverno.io/v1', 'Policy')):
            try:
                existing = client.list_resource(api_version, kind)
            except Exception:  # noqa: BLE001 - kind may not be served
                continue
            for resource in existing:
                resource.setdefault('kind', kind)
                self._sync_rule_info(Policy(resource))

    @staticmethod
    def _labels(policy: Policy) -> dict:
        return {
            'policy_name': policy.name,
            'policy_namespace': policy.namespace or '-',
            'policy_type': 'cluster' if not policy.namespace
            else 'namespaced',
            'policy_validation_mode':
                str(policy.validation_failure_action).lower(),
            'policy_background_mode': str(bool(policy.background)).lower(),
        }

    def _on_event(self, event: str, resource: dict) -> None:
        if resource.get('kind') not in _POLICY_KINDS:
            return
        policy = Policy(resource)
        labels = self._labels(policy)
        change = {'create': 'created', 'update': 'updated',
                  'delete': 'deleted',
                  'ADDED': 'created', 'MODIFIED': 'updated',
                  'DELETED': 'deleted'}.get(event, event)
        self.registry.inc(POLICY_CHANGES,
                          policy_change_type=change, **labels)
        self._sync_rule_info(policy, deleted=change == 'deleted')

    def _sync_rule_info(self, policy: Policy, deleted: bool = False) -> None:
        labels = self._labels(policy)
        key = f'{policy.namespace}/{policy.name}'
        with self._lock:
            # retract the previous rule-info series for this policy —
            # the rule no longer exists, so the series is removed from
            # exposition entirely (set_gauge(0) would keep it visible)
            for old in self._rules.pop(key, set()):
                self.registry.clear_gauge(POLICY_RULE_INFO,
                                          **dict(old))
            if deleted:
                return
            current = set()
            for rule in policy.rules:
                rule_labels: Tuple = tuple(sorted({
                    **labels,
                    'rule_name': rule.name,
                    'rule_type': _rule_type(rule),
                }.items()))
                current.add(rule_labels)
                self.registry.set_gauge(POLICY_RULE_INFO, 1.0,
                                        **dict(rule_labels))
            self._rules[key] = current


def _rule_type(rule) -> str:
    if rule.has_validate():
        return 'validate'
    if rule.has_mutate():
        return 'mutate'
    if rule.has_generate():
        return 'generate'
    if rule.verify_images:
        return 'verifyImages'
    return 'unknown'
