"""OpenAPI schema sync controller.

Mirrors the reference's periodic schema ingestion (reference:
pkg/controllers/openapi/controller.go:148 — the controller polls the
cluster's OpenAPI document and CRDs, feeding pkg/openapi.Manager).  Here
the cluster source is the dynamic client: every
``CustomResourceDefinition`` in the cluster has its structural
``openAPIV3Schema`` converted to the manager's dotted path→type form, so
mutations of CRD instances are schema-checked exactly like core kinds.
The built-in core snapshot (openapi/manager.py) is the fallback tier,
matching the reference's baked-in ``data/apiResources.go``.
"""

from __future__ import annotations

from typing import Any, Dict

from ..openapi.manager import Manager

_TYPE_MAP = {'object': 'object', 'array': 'array', 'string': 'string',
             'integer': 'integer', 'boolean': 'boolean',
             'number': 'number'}


def schema_to_fields(schema: dict, prefix: str = '',
                     out: Dict[str, str] = None,
                     depth: int = 0) -> Dict[str, str]:
    """Flatten an openAPIV3Schema's properties into dotted paths.

    ``additionalProperties: {type: string}`` objects become 'string-map';
    array item schemas are not descended (the manager validates spines,
    element checks stay with the engine), matching the structural level
    the reference's ValidateResource enforces."""
    if out is None:
        out = {}
    if depth > 8 or not isinstance(schema, dict):
        return out
    for name, sub in (schema.get('properties') or {}).items():
        if not isinstance(sub, dict):
            continue
        path = f'{prefix}{name}'
        stype = sub.get('type', '')
        addl = sub.get('additionalProperties')
        if stype == 'object' and isinstance(addl, dict) and \
                addl.get('type') == 'string':
            out[path] = 'string-map'
        elif stype in _TYPE_MAP:
            out[path] = _TYPE_MAP[stype]
        if stype == 'object':
            schema_to_fields(sub, f'{path}.', out, depth + 1)
    return out


class OpenAPIController:
    """reference: pkg/controllers/openapi/controller.go (2m resync)."""

    def __init__(self, client, manager: Manager):
        self.client = client
        self.manager = manager

    def reconcile(self) -> int:
        """Ingest every CRD's schema into the manager (full replace, so
        deleted or retyped CRDs leave no stale entries); returns the
        number of (group, kind) schemas synced."""
        try:
            crds = self.client.list_resource(
                'apiextensions.k8s.io/v1', 'CustomResourceDefinition', '')
        except Exception:  # noqa: BLE001 - no CRDs registered
            crds = []
        schemas: Dict[tuple, Dict[str, str]] = {}
        for crd in crds:
            spec = crd.get('spec') or {}
            group = spec.get('group') or ''
            kind = ((spec.get('names') or {}).get('kind')) or ''
            if not kind:
                continue
            versions = spec.get('versions') or []
            # the storage (or first) version's schema wins, like the
            # reference's single-document sync
            chosen = next((v for v in versions if v.get('storage')),
                          versions[0] if versions else None)
            if not chosen:
                continue
            schema = ((chosen.get('schema') or {})
                      .get('openAPIV3Schema')) or {}
            fields = schema_to_fields(schema)
            if fields:
                schemas[(group, kind)] = fields
        self.manager.replace_crd_schemas(schemas)
        return len(schemas)


def crd_fixture(group: str, kind: str, plural: str,
                open_api_v3_schema: dict,
                version: str = 'v1') -> dict:
    """A minimal CustomResourceDefinition document (test/scenario aid)."""
    return {
        'apiVersion': 'apiextensions.k8s.io/v1',
        'kind': 'CustomResourceDefinition',
        'metadata': {'name': f'{plural}.{group}'},
        'spec': {
            'group': group,
            'names': {'kind': kind, 'plural': plural},
            'scope': 'Namespaced',
            'versions': [{
                'name': version, 'served': True, 'storage': True,
                'schema': {'openAPIV3Schema': open_api_v3_schema},
            }],
        },
    }
