"""Cleanup controller: CleanupPolicy / ClusterCleanupPolicy execution.

The reference reconciles a CronJob per cleanup policy whose schedule
POSTs back to the cleanup webhook, which deletes the matching resources
(reference: pkg/controllers/cleanup/controller.go:164 buildCronJob,
cmd/cleanup-controller/handlers/cleanup/handlers.go).  Here the cron
schedule is evaluated in-process: ``tick(now)`` runs every due policy's
deletion pass — the same match + conditions semantics — against the
dynamic client.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..api.policy import Policy, Rule
from ..api.unstructured import Resource
from ..engine import operators
from ..engine.api import PolicyContext
from ..engine.context import Context
from ..engine.match import matches_resource_description
from ..engine.variables import substitute_all


def parse_cron(expr: str) -> Tuple[set, set, set, set, set]:
    """Standard 5-field cron (minute hour dom month dow)."""
    fields = expr.split()
    if len(fields) != 5:
        raise ValueError(f'invalid cron expression {expr!r}')
    ranges = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]
    out = []
    for field, (lo, hi) in zip(fields, ranges):
        vals = set()
        for part in field.split(','):
            step = 1
            if '/' in part:
                part, step_s = part.split('/', 1)
                step = int(step_s)
            if part == '*':
                start, end = lo, hi
            elif '-' in part:
                start_s, end_s = part.split('-', 1)
                start, end = int(start_s), int(end_s)
            else:
                start = end = int(part)
            vals.update(range(start, end + 1, step))
        out.append(vals)
    return tuple(out)


def validate_cleanup_policy_auth(doc: dict, client) -> Optional[str]:
    """Permission pre-flight for a CleanupPolicy: the controller must be
    able to 'delete' and 'list' every matched kind (reference:
    pkg/validation/cleanuppolicy/validate.go:67 validateAuth).  Returns
    an error string or None."""
    from ..auth import CanI
    namespace = ((doc.get('metadata') or {}).get('namespace') or '')
    spec = doc.get('spec') or {}
    match = spec.get('match') or {}
    kinds = set()
    for f in [match] + (match.get('any') or []) + (match.get('all') or []):
        kinds.update((f.get('resources') or {}).get('kinds') or [])
    for kind in sorted(kinds):
        if not CanI(client, kind, namespace, 'delete').run_access_check():
            return (f'cleanup controller has no permission to delete '
                    f'kind {kind}')
        if not CanI(client, kind, namespace, 'list').run_access_check():
            return (f'cleanup controller has no permission to list '
                    f'kind {kind}')
    return None


def validate_cleanup_admission(request: dict, client) -> dict:
    """CleanupPolicy admission response: structural checks (schedule,
    match) then the delete/list permission pre-flight (reference:
    cmd/cleanup-controller/handlers/admission/policy.go Validate →
    pkg/validation/cleanuppolicy/validate.go)."""
    from ..webhooks import admission
    uid = request.get('uid', '')
    doc = admission.request_resource(request) or {}
    spec = doc.get('spec') or {}
    try:
        parse_cron(str(spec.get('schedule', '')))
    except ValueError as e:
        return admission.response(uid, False, str(e))
    match = spec.get('match')
    if not match:
        return admission.response(uid, False, 'spec.match is required')
    # user infos are not allowed in cleanup match statements (reference:
    # api/kyverno/v2alpha1 cleanup_policy_types ValidateMatchResources →
    # match.GetUserInfo() must be empty)
    for f in [match] + (match.get('any') or []) + (match.get('all') or []):
        if f.get('subjects') or f.get('roles') or f.get('clusterRoles'):
            return admission.response(
                uid, False,
                'cleanup policies do not support user infos in match: '
                'not allowed here')
    err = validate_cleanup_policy_auth(doc, client)
    if err is not None:
        return admission.response(uid, False, err)
    return admission.response(uid, True)


def cron_matches(expr: str, ts: float) -> bool:
    minute, hour, dom, month, dow = parse_cron(expr)
    t = time.gmtime(ts)
    return (t.tm_min in minute and t.tm_hour in hour and
            t.tm_mday in dom and t.tm_mon in month and
            (t.tm_wday + 1) % 7 in dow)


class CleanupController:
    """reference: pkg/controllers/cleanup/controller.go +
    cmd/cleanup-controller/handlers/cleanup"""

    def __init__(self, client):
        self.client = client
        self._lock = threading.Lock()
        self._policies: Dict[str, dict] = {}
        self._last_run: Dict[str, int] = {}

    def set_policy(self, doc: dict) -> None:
        key = self._key(doc)
        with self._lock:
            self._policies[key] = doc

    def delete_policy(self, doc: dict) -> None:
        with self._lock:
            self._policies.pop(self._key(doc), None)

    def retain_policies(self, keys) -> None:
        """Drop tracked policies not in ``keys`` (cluster-sync prune)."""
        keys = set(keys)
        with self._lock:
            for key in list(self._policies):
                if key not in keys:
                    del self._policies[key]

    @staticmethod
    def _key(doc: dict) -> str:
        meta = doc.get('metadata') or {}
        ns = meta.get('namespace', '')
        return f"{ns}/{meta.get('name', '')}" if ns else meta.get('name', '')

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """Run every policy whose schedule matches the current minute;
        returns the deleted resources."""
        now = now or time.time()
        minute = int(now // 60)
        deleted: List[dict] = []
        with self._lock:
            policies = dict(self._policies)
        for key, doc in policies.items():
            schedule = (doc.get('spec') or {}).get('schedule', '')
            if not schedule:
                continue
            if self._last_run.get(key) == minute:
                continue
            try:
                due = cron_matches(schedule, now)
            except ValueError:
                continue
            if not due:
                continue
            self._last_run[key] = minute
            deleted.extend(self.cleanup(doc))
        return deleted

    CLEANUP_SERVICE_PATH = '/cleanup'  # reference: controller.go:28

    def reconcile_cronjobs(self, namespace: str = 'kyverno',
                           service: str = 'https://cleanup-controller.'
                                          'kyverno.svc') -> List[dict]:
        """Materialize one CronJob CR per cleanup policy whose schedule
        calls back the ``/cleanup`` endpoint — the reference's externally
        visible deployment contract (reference:
        pkg/controllers/cleanup/controller.go:164 buildCronJob).  Stale
        CronJobs of deleted policies are removed.  Returns the CronJobs.
        """
        with self._lock:
            policies = dict(self._policies)
        desired = {}
        for key, doc in policies.items():
            meta = doc.get('metadata') or {}
            pol_ns = meta.get('namespace', '')
            kind = 'CleanupPolicy' if pol_ns else 'ClusterCleanupPolicy'
            # a flat ns+name join is ambiguous ('a-b' vs ns a / name b);
            # an 8-hex digest of kind+key makes the CronJob name unique
            # per policy and keeps it inside the 52-char CronJob limit
            digest = hashlib.sha256(f'{kind}/{key}'.encode()) \
                .hexdigest()[:8]
            base = f"cleanup-{pol_ns}-{meta.get('name', '')}" if pol_ns \
                else f"cleanup-{meta.get('name', '')}"
            name = f'{base[:43].rstrip("-")}-{digest}'
            cronjob = {
                'apiVersion': 'batch/v1', 'kind': 'CronJob',
                'metadata': {
                    'name': name, 'namespace': namespace,
                    'ownerReferences': [{
                        'apiVersion': 'kyverno.io/v2alpha1',
                        'kind': kind, 'name': meta.get('name', ''),
                        'uid': meta.get('uid', ''),
                    }],
                },
                'spec': {
                    'schedule': (doc.get('spec') or {}).get('schedule', ''),
                    'successfulJobsHistoryLimit': 0,
                    'failedJobsHistoryLimit': 1,
                    'concurrencyPolicy': 'Forbid',
                    'jobTemplate': {'spec': {'template': {'spec': {
                        'restartPolicy': 'OnFailure',
                        'containers': [{
                            'name': 'cleanup',
                            'image': 'curlimages/curl:7.86.0',
                            'args': [
                                '-k',
                                f'{service}'
                                f'{self.CLEANUP_SERVICE_PATH}'
                                f'?policy={key}'],
                            'securityContext': {
                                'allowPrivilegeEscalation': False,
                                'runAsNonRoot': True,
                                'runAsUser': 1000,
                                'seccompProfile': {'type': 'RuntimeDefault'},
                                'capabilities': {'drop': ['ALL']},
                            },
                        }],
                    }}}},
                },
            }
            desired[name] = cronjob
        out = []
        for name, cronjob in desired.items():
            try:
                existing = self.client.get_resource(
                    'batch/v1', 'CronJob', namespace, name)
            except Exception:  # noqa: BLE001
                existing = None
            if existing is None:
                out.append(self.client.create_resource(
                    'batch/v1', 'CronJob', namespace, cronjob))
            elif (existing.get('spec') == cronjob['spec'] and
                  existing['metadata'].get('ownerReferences') ==
                  cronjob['metadata']['ownerReferences']):
                # unchanged: no write (the reference controller compares
                # observed vs desired before updating)
                out.append(existing)
            else:
                existing['spec'] = cronjob['spec']
                existing['metadata']['ownerReferences'] = \
                    cronjob['metadata']['ownerReferences']
                out.append(self.client.update_resource(
                    'batch/v1', 'CronJob', namespace, existing))
        try:
            for cj in self.client.list_resource('batch/v1', 'CronJob',
                                                namespace, None):
                name = (cj.get('metadata') or {}).get('name', '')
                if name.startswith('cleanup-') and name not in desired:
                    self.client.delete_resource('batch/v1', 'CronJob',
                                                namespace, name)
        except Exception:  # noqa: BLE001
            pass
        return out

    def handle_cleanup_request(self, policy_key: str) -> List[dict]:
        """The ``/cleanup?policy=ns/name`` endpoint body (reference:
        cmd/cleanup-controller/handlers/cleanup/handlers.go)."""
        with self._lock:
            doc = self._policies.get(policy_key)
        if doc is None:
            raise KeyError(policy_key)
        return self.cleanup(doc)

    def cleanup(self, doc: dict) -> List[dict]:
        """One deletion pass for a cleanup policy
        (reference: handlers/cleanup/handlers.go executePolicy)."""
        spec = doc.get('spec') or {}
        meta = doc.get('metadata') or {}
        policy_ns = meta.get('namespace', '')
        match = spec.get('match') or {}
        exclude = spec.get('exclude') or {}
        conditions = spec.get('conditions')
        rule = Rule({'name': 'cleanup', 'match': match, 'exclude': exclude})
        kinds = set()
        for f in [match] + (match.get('any') or []) + \
                (match.get('all') or []):
            for k in (f.get('resources') or {}).get('kinds') or []:
                kinds.add(str(k).split('/')[-1])
        deleted = []
        for kind in sorted(kinds):
            try:
                items = self.client.list_resource('', kind, policy_ns, None)
            except Exception:  # noqa: BLE001
                continue
            for item in items:
                r = Resource(item)
                if matches_resource_description(
                        r, rule, None, [], {}, '') is not None:
                    continue
                if conditions is not None and \
                        not self._conditions_met(conditions, item):
                    continue
                try:
                    self.client.delete_resource(
                        item.get('apiVersion', ''), r.kind,
                        r.namespace, r.name)
                    deleted.append(item)
                except Exception:  # noqa: BLE001
                    continue
        return deleted

    def _conditions_met(self, conditions: Any, resource: dict) -> bool:
        ctx = Context()
        ctx.add_resource(resource)
        # cleanup conditions address the candidate as {{ target.* }}
        # (reference: cmd/cleanup-controller/handlers/cleanup/handlers.go
        # enginectx.AddTargetResource)
        ctx.add_target_resource(resource)
        try:
            substituted = substitute_all(ctx, conditions)
        except Exception:  # noqa: BLE001
            return False
        return operators.evaluate_conditions(ctx, substituted)
