"""Cleanup controller: CleanupPolicy / ClusterCleanupPolicy execution.

The reference reconciles a CronJob per cleanup policy whose schedule
POSTs back to the cleanup webhook, which deletes the matching resources
(reference: pkg/controllers/cleanup/controller.go:164 buildCronJob,
cmd/cleanup-controller/handlers/cleanup/handlers.go).  Here the cron
schedule is evaluated in-process: ``tick(now)`` runs every due policy's
deletion pass — the same match + conditions semantics — against the
dynamic client.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..api.policy import Policy, Rule
from ..api.unstructured import Resource
from ..engine import operators
from ..engine.api import PolicyContext
from ..engine.context import Context
from ..engine.match import matches_resource_description
from ..engine.variables import substitute_all


def parse_cron(expr: str) -> Tuple[set, set, set, set, set]:
    """Standard 5-field cron (minute hour dom month dow)."""
    fields = expr.split()
    if len(fields) != 5:
        raise ValueError(f'invalid cron expression {expr!r}')
    ranges = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]
    out = []
    for field, (lo, hi) in zip(fields, ranges):
        vals = set()
        for part in field.split(','):
            step = 1
            if '/' in part:
                part, step_s = part.split('/', 1)
                step = int(step_s)
            if part == '*':
                start, end = lo, hi
            elif '-' in part:
                start_s, end_s = part.split('-', 1)
                start, end = int(start_s), int(end_s)
            else:
                start = end = int(part)
            vals.update(range(start, end + 1, step))
        out.append(vals)
    return tuple(out)


def cron_matches(expr: str, ts: float) -> bool:
    minute, hour, dom, month, dow = parse_cron(expr)
    t = time.gmtime(ts)
    return (t.tm_min in minute and t.tm_hour in hour and
            t.tm_mday in dom and t.tm_mon in month and
            (t.tm_wday + 1) % 7 in dow)


class CleanupController:
    """reference: pkg/controllers/cleanup/controller.go +
    cmd/cleanup-controller/handlers/cleanup"""

    def __init__(self, client):
        self.client = client
        self._lock = threading.Lock()
        self._policies: Dict[str, dict] = {}
        self._last_run: Dict[str, int] = {}

    def set_policy(self, doc: dict) -> None:
        key = self._key(doc)
        with self._lock:
            self._policies[key] = doc

    def delete_policy(self, doc: dict) -> None:
        with self._lock:
            self._policies.pop(self._key(doc), None)

    @staticmethod
    def _key(doc: dict) -> str:
        meta = doc.get('metadata') or {}
        ns = meta.get('namespace', '')
        return f"{ns}/{meta.get('name', '')}" if ns else meta.get('name', '')

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """Run every policy whose schedule matches the current minute;
        returns the deleted resources."""
        now = now or time.time()
        minute = int(now // 60)
        deleted: List[dict] = []
        with self._lock:
            policies = dict(self._policies)
        for key, doc in policies.items():
            schedule = (doc.get('spec') or {}).get('schedule', '')
            if not schedule:
                continue
            if self._last_run.get(key) == minute:
                continue
            try:
                due = cron_matches(schedule, now)
            except ValueError:
                continue
            if not due:
                continue
            self._last_run[key] = minute
            deleted.extend(self.cleanup(doc))
        return deleted

    def cleanup(self, doc: dict) -> List[dict]:
        """One deletion pass for a cleanup policy
        (reference: handlers/cleanup/handlers.go executePolicy)."""
        spec = doc.get('spec') or {}
        meta = doc.get('metadata') or {}
        policy_ns = meta.get('namespace', '')
        match = spec.get('match') or {}
        exclude = spec.get('exclude') or {}
        conditions = spec.get('conditions')
        rule = Rule({'name': 'cleanup', 'match': match, 'exclude': exclude})
        kinds = set()
        for f in [match] + (match.get('any') or []) + \
                (match.get('all') or []):
            for k in (f.get('resources') or {}).get('kinds') or []:
                kinds.add(str(k).split('/')[-1])
        deleted = []
        for kind in sorted(kinds):
            try:
                items = self.client.list_resource('', kind, policy_ns, None)
            except Exception:  # noqa: BLE001
                continue
            for item in items:
                r = Resource(item)
                if matches_resource_description(
                        r, rule, None, [], {}, '') is not None:
                    continue
                if conditions is not None and \
                        not self._conditions_met(conditions, item):
                    continue
                try:
                    self.client.delete_resource(
                        item.get('apiVersion', ''), r.kind,
                        r.namespace, r.name)
                    deleted.append(item)
                except Exception:  # noqa: BLE001
                    continue
        return deleted

    def _conditions_met(self, conditions: Any, resource: dict) -> bool:
        ctx = Context()
        ctx.add_resource(resource)
        try:
            substituted = substitute_all(ctx, conditions)
        except Exception:  # noqa: BLE001
            return False
        return operators.evaluate_conditions(ctx, substituted)
