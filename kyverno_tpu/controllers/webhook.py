"""Webhook configuration reconciler.

Builds Validating/MutatingWebhookConfigurations from the live policy set
— narrow per-kind rules in fine-grained mode, a wildcard default
otherwise — injects the CA bundle, and maintains the lease heartbeat the
readiness watchdog checks (reference:
pkg/controllers/webhook/controller.go:215 watchdog, :617
buildResourceMutatingWebhookConfiguration, :692
buildDefaultResourceValidatingWebhookConfiguration).
"""

from __future__ import annotations

import base64
import time
from typing import Dict, List, Optional, Set

from ..api.policy import Policy

DEFAULT_WEBHOOK_TIMEOUT = 10  # reference: webhook/controller.go:49

VALIDATING_NAME = 'kyverno-resource-validating-webhook-cfg'
MUTATING_NAME = 'kyverno-resource-mutating-webhook-cfg'
# static control-plane webhooks (reference: pkg/config/config.go:22-34)
POLICY_VALIDATING_NAME = 'kyverno-policy-validating-webhook-cfg'
POLICY_MUTATING_NAME = 'kyverno-policy-mutating-webhook-cfg'
VERIFY_MUTATING_NAME = 'kyverno-verify-mutating-webhook-cfg'
#: stamped on every managed webhook configuration
#: (reference: pkg/utils/kube ManagedByLabel via webhook/utils.go:101)
MANAGED_BY_LABELS = {'webhook.kyverno.io/managed-by': 'kyverno'}
LEASE_NAME = 'kyverno-health'
# watchdog heartbeat: every 10s, stale after 100s
# (reference: webhook/controller.go:215-275, IdleDeadline)
TICK = 10.0
IDLE_DEADLINE = 100.0

# kinds → (apiGroups, apiVersions, resources) for webhook rules; the
# reference resolves these via discovery — this static table covers the
# built-in workload/core kinds, discovery extends it at runtime
_KIND_RESOURCES = {
    'Pod': ('', 'v1', 'pods'),
    'Namespace': ('', 'v1', 'namespaces'),
    'ConfigMap': ('', 'v1', 'configmaps'),
    'Secret': ('', 'v1', 'secrets'),
    'Service': ('', 'v1', 'services'),
    'ServiceAccount': ('', 'v1', 'serviceaccounts'),
    'Deployment': ('apps', 'v1', 'deployments'),
    'DaemonSet': ('apps', 'v1', 'daemonsets'),
    'StatefulSet': ('apps', 'v1', 'statefulsets'),
    'ReplicaSet': ('apps', 'v1', 'replicasets'),
    'Job': ('batch', 'v1', 'jobs'),
    'CronJob': ('batch', 'v1', 'cronjobs'),
    'Ingress': ('networking.k8s.io', 'v1', 'ingresses'),
    'NetworkPolicy': ('networking.k8s.io', 'v1', 'networkpolicies'),
    'LimitRange': ('', 'v1', 'limitranges'),
    'ResourceQuota': ('', 'v1', 'resourcequotas'),
}


def _policy_kinds(policies: List[Policy], want) -> Dict[str, Set[str]]:
    """kinds with their failure actions for the selected rule types."""
    from ..config.toggle import FORCE_FAILURE_POLICY_IGNORE
    force_ignore = FORCE_FAILURE_POLICY_IGNORE.enabled()
    kinds: Dict[str, Set[str]] = {}
    for policy in policies:
        # env-tier toggle (reference: pkg/toggle/toggle.go:23
        # ForceFailurePolicyIgnore)
        fail_policy = 'Ignore' if force_ignore else \
            (policy.spec.get('failurePolicy') or 'Fail')
        for rule in policy.rules:
            if not want(rule):
                continue
            match = rule.raw.get('match') or {}
            for f in [match] + (match.get('any') or []) + \
                    (match.get('all') or []):
                for k in (f.get('resources') or {}).get('kinds') or []:
                    kinds.setdefault(str(k).split('/')[-1],
                                     set()).add(fail_policy)
    return kinds


def _rules_for(kinds: Dict[str, Set[str]]) -> List[dict]:
    groups: Dict[tuple, List[str]] = {}
    wildcard = False
    for kind in sorted(kinds):
        if '*' in kind:
            wildcard = True
            continue
        entry = _KIND_RESOURCES.get(kind)
        if entry is None:
            wildcard = True  # unknown kind → fall back to wildcard rule
            continue
        group, version, resource = entry
        groups.setdefault((group, version), []).append(resource)
    rules = [{'apiGroups': [g], 'apiVersions': [v],
              'resources': sorted(res), 'scope': '*'}
             for (g, v), res in sorted(groups.items())]
    if wildcard:
        rules = [{'apiGroups': ['*'], 'apiVersions': ['*'],
                  'resources': ['*/*'], 'scope': '*'}]
    return rules


class WebhookConfigReconciler:
    """reference: pkg/controllers/webhook/controller.go:904 (NewController)"""

    def __init__(self, client, ca_bundle: bytes = b'',
                 namespace: str = 'kyverno', service: str = 'kyverno-svc',
                 timeout: int = DEFAULT_WEBHOOK_TIMEOUT):
        self.client = client
        self.ca_bundle = ca_bundle
        self.namespace = namespace
        self.service = service
        self.timeout = timeout

    def _client_config(self, path: str) -> dict:
        return {
            'service': {'name': self.service, 'namespace': self.namespace,
                        'path': path, 'port': 443},
            'caBundle': base64.b64encode(self.ca_bundle).decode(),
        }

    def reconcile(self, policies: List[Policy]) -> None:
        self._apply(VALIDATING_NAME, 'ValidatingWebhookConfiguration',
                    self._build_validating(policies))
        self._apply(MUTATING_NAME, 'MutatingWebhookConfiguration',
                    self._build_mutating(policies))
        self._apply(POLICY_VALIDATING_NAME,
                    'ValidatingWebhookConfiguration',
                    self._build_policy_validating())
        self._apply(POLICY_MUTATING_NAME, 'MutatingWebhookConfiguration',
                    self._build_policy_mutating())
        self._apply(VERIFY_MUTATING_NAME, 'MutatingWebhookConfiguration',
                    self._build_verify_mutating())
        self._update_policy_statuses(policies)

    #: kyverno.io policy CRs (reference: webhook/controller.go:62
    #: policyRule) and the health lease (:67 verifyRule)
    _POLICY_RULE = {'apiGroups': ['kyverno.io'],
                    'apiVersions': ['v1', 'v2beta1'],
                    'resources': ['clusterpolicies/*', 'policies/*']}
    _VERIFY_RULE = {'apiGroups': ['coordination.k8s.io'],
                    'apiVersions': ['v1'], 'resources': ['leases']}

    def _build_policy_validating(self) -> dict:
        """reference: controller.go:569
        buildPolicyValidatingWebhookConfiguration"""
        return {
            'apiVersion': 'admissionregistration.k8s.io/v1',
            'kind': 'ValidatingWebhookConfiguration',
            'metadata': {'name': POLICY_VALIDATING_NAME,
                         'labels': dict(MANAGED_BY_LABELS)},
            'webhooks': [{
                'name': 'validate-policy.kyverno.svc',
                'clientConfig': self._client_config('/policyvalidate'),
                'rules': [dict(self._POLICY_RULE,
                               operations=['CREATE', 'UPDATE'])],
                'failurePolicy': 'Fail',
                'sideEffects': 'None',
                'admissionReviewVersions': ['v1'],
            }],
        }

    def _build_policy_mutating(self) -> dict:
        """reference: controller.go:548
        buildPolicyMutatingWebhookConfiguration"""
        return {
            'apiVersion': 'admissionregistration.k8s.io/v1',
            'kind': 'MutatingWebhookConfiguration',
            'metadata': {'name': POLICY_MUTATING_NAME,
                         'labels': dict(MANAGED_BY_LABELS)},
            'webhooks': [{
                'name': 'mutate-policy.kyverno.svc',
                'clientConfig': self._client_config('/policymutate'),
                'rules': [dict(self._POLICY_RULE,
                               operations=['CREATE', 'UPDATE'])],
                'failurePolicy': 'Fail',
                'sideEffects': 'NoneOnDryRun',
                'reinvocationPolicy': 'IfNeeded',
                'admissionReviewVersions': ['v1'],
            }],
        }

    def _build_verify_mutating(self) -> dict:
        """reference: controller.go:521
        buildVerifyMutatingWebhookConfiguration"""
        return {
            'apiVersion': 'admissionregistration.k8s.io/v1',
            'kind': 'MutatingWebhookConfiguration',
            'metadata': {'name': VERIFY_MUTATING_NAME,
                         'labels': dict(MANAGED_BY_LABELS)},
            'webhooks': [{
                'name': 'monitor-webhooks.kyverno.svc',
                'clientConfig': self._client_config('/verifymutate'),
                'rules': [dict(self._VERIFY_RULE, operations=['UPDATE'])],
                'failurePolicy': 'Ignore',
                'sideEffects': 'NoneOnDryRun',
                'reinvocationPolicy': 'IfNeeded',
                'admissionReviewVersions': ['v1'],
                'objectSelector': {'matchLabels': {
                    'app.kubernetes.io/name': 'kyverno'}},
            }],
        }

    def _build_validating(self, policies: List[Policy]) -> dict:
        kinds = _policy_kinds(
            policies, lambda r: r.has_validate() or r.has_generate())
        webhooks = []
        for fail_policy, suffix in (('Fail', '/fail'), ('Ignore', '/ignore')):
            sel = {k: v for k, v in kinds.items() if fail_policy in v}
            if not sel:
                continue
            webhooks.append({
                'name': f'validate{suffix.replace("/", ".")}.kyverno.svc',
                'clientConfig': self._client_config(f'/validate{suffix}'),
                'rules': [dict(r, operations=['CREATE', 'UPDATE', 'DELETE',
                                              'CONNECT'])
                          for r in _rules_for(sel)],
                'failurePolicy': fail_policy,
                'sideEffects': 'NoneOnDryRun',
                'admissionReviewVersions': ['v1'],
                'timeoutSeconds': self.timeout,
            })
        if not webhooks:
            # no policies installed: the default catch-all ignore webhook
            # (reference: controller.go
            # buildDefaultResourceValidatingWebhookConfiguration)
            webhooks.append({
                'name': 'validate.kyverno.svc-ignore',
                'clientConfig': self._client_config('/validate/ignore'),
                'rules': [{'apiGroups': ['*'], 'apiVersions': ['*'],
                           'resources': ['*/*'],
                           'operations': ['CREATE', 'UPDATE', 'DELETE',
                                          'CONNECT']}],
                'failurePolicy': 'Ignore',
                'sideEffects': 'NoneOnDryRun',
                'admissionReviewVersions': ['v1'],
                'timeoutSeconds': self.timeout,
            })
        return {
            'apiVersion': 'admissionregistration.k8s.io/v1',
            'kind': 'ValidatingWebhookConfiguration',
            'metadata': {'name': VALIDATING_NAME,
                         'labels': dict(MANAGED_BY_LABELS)},
            'webhooks': webhooks,
        }

    def _build_mutating(self, policies: List[Policy]) -> dict:
        kinds = _policy_kinds(
            policies,
            lambda r: r.has_mutate() or r.has_verify_images())
        webhooks = []
        for fail_policy, suffix in (('Fail', '/fail'), ('Ignore', '/ignore')):
            sel = {k: v for k, v in kinds.items() if fail_policy in v}
            if not sel:
                continue
            webhooks.append({
                'name': f'mutate{suffix.replace("/", ".")}.kyverno.svc',
                'clientConfig': self._client_config(f'/mutate{suffix}'),
                'rules': [dict(r, operations=['CREATE', 'UPDATE'])
                          for r in _rules_for(sel)],
                'failurePolicy': fail_policy,
                'sideEffects': 'NoneOnDryRun',
                'admissionReviewVersions': ['v1'],
                'timeoutSeconds': self.timeout,
            })
        if not webhooks:
            # reference: controller.go
            # buildDefaultResourceMutatingWebhookConfiguration
            webhooks.append({
                'name': 'mutate.kyverno.svc-ignore',
                'clientConfig': self._client_config('/mutate/ignore'),
                'rules': [{'apiGroups': ['*'], 'apiVersions': ['*'],
                           'resources': ['*/*'],
                           'operations': ['CREATE', 'UPDATE']}],
                'failurePolicy': 'Ignore',
                'sideEffects': 'NoneOnDryRun',
                'admissionReviewVersions': ['v1'],
                'timeoutSeconds': self.timeout,
            })
        return {
            'apiVersion': 'admissionregistration.k8s.io/v1',
            'kind': 'MutatingWebhookConfiguration',
            'metadata': {'name': MUTATING_NAME,
                         'labels': dict(MANAGED_BY_LABELS)},
            'webhooks': webhooks,
        }

    def _apply(self, name: str, kind: str, desired: dict) -> None:
        existing = None
        try:
            existing = self.client.get_resource(
                'admissionregistration.k8s.io/v1', kind, '', name)
        except Exception:  # noqa: BLE001
            existing = None
        if not desired['webhooks']:
            if existing is not None:
                self.client.delete_resource(
                    'admissionregistration.k8s.io/v1', kind, '', name)
            return
        if existing is None:
            self.client.create_resource(
                'admissionregistration.k8s.io/v1', kind, '', desired)
        else:
            existing['webhooks'] = desired['webhooks']
            self.client.update_resource(
                'admissionregistration.k8s.io/v1', kind, '', existing)

    def _update_policy_statuses(self, policies: List[Policy]) -> None:
        """Mark policies ready once their webhooks exist, persisting the
        Ready condition, the computed autogen rules and the per-type
        rule counts to the live CR the way the reference's status
        subresource update does (controller.go:426 updatePolicyStatuses
        + utils.go:111 setRuleCount; condition shape: api/kyverno/v1
        IsReady/SetReady)."""
        from ..autogen.autogen import compute_rules
        for policy in policies:
            rules = compute_rules(policy)
            counts = {'validate': 0, 'generate': 0, 'mutate': 0,
                      'verifyimages': 0}
            autogen_rules = []
            for rule in rules:
                if str(rule.get('name', '')).startswith('autogen-'):
                    autogen_rules.append(rule)
                    continue
                if rule.get('validate') is not None:
                    counts['validate'] += 1
                if rule.get('generate') is not None:
                    counts['generate'] += 1
                if rule.get('mutate') is not None:
                    counts['mutate'] += 1
                if rule.get('verifyImages') is not None:
                    counts['verifyimages'] += 1
            status = {
                'ready': True,
                'conditions': [{'type': 'Ready', 'status': 'True',
                                'reason': 'Succeeded'}],
                'autogen': {'rules': autogen_rules},
                'rulecount': counts,
            }
            policy.raw.setdefault('status', {}).update(status)
            kind = policy.raw.get('kind', 'ClusterPolicy')
            api_version = policy.raw.get('apiVersion', 'kyverno.io/v1')
            try:
                live = self.client.get_resource(
                    api_version, kind, policy.namespace or '', policy.name)
                live_status = live.get('status') or {}
                if all(live_status.get(k) == v for k, v in status.items()):
                    continue  # already current: no steady-state writes
                live.setdefault('status', {}).update(status)
                self.client.update_status_resource(
                    api_version, kind, policy.namespace or '', live)
            except Exception:  # noqa: BLE001 - ad-hoc policies in unit
                # tests are not stored as CRs; readiness is best-effort
                pass

    # -- watchdog lease ---------------------------------------------------

    def heartbeat(self, now: Optional[float] = None) -> dict:
        """Renew the health lease (reference: controller.go:215)."""
        now = now or time.time()
        stamp = time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime(now))
        try:
            lease = self.client.get_resource(
                'coordination.k8s.io/v1', 'Lease', self.namespace,
                LEASE_NAME)
        except Exception:  # noqa: BLE001
            lease = None
        if lease is None:
            return self.client.create_resource(
                'coordination.k8s.io/v1', 'Lease', self.namespace, {
                    'apiVersion': 'coordination.k8s.io/v1', 'kind': 'Lease',
                    'metadata': {'name': LEASE_NAME,
                                 'namespace': self.namespace,
                                 'annotations': {
                                     'kyverno.io/last-request-time': stamp}},
                    'spec': {'renewTime': stamp}})
        lease.setdefault('metadata', {}).setdefault('annotations', {})[
            'kyverno.io/last-request-time'] = stamp
        lease.setdefault('spec', {})['renewTime'] = stamp
        return self.client.update_resource(
            'coordination.k8s.io/v1', 'Lease', self.namespace, lease)
