"""Leader election.

Two modes, mirroring SURVEY §2.6's mapping of the reference's
client-go lease election (reference: pkg/leaderelection/
leaderelection.go:51 New):

* **Lease mode** — lease CRs through the dynamic client, for running
  multiple replicas against a shared API server like the reference.
* **Mesh mode** — under ``jax.distributed`` the leader is process 0 of
  the initialized process group: a single deterministic leader per
  slice with no extra coordination traffic (the TPU-native equivalent
  of one elected replica driving the reconcilers).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Optional

LEASE_DURATION = 15.0   # reference: leaderelection.go LeaseDuration
RENEW_DEADLINE = 10.0
RETRY_PERIOD = 2.0


def _to_microtime(ts: float) -> str:
    """coordination.k8s.io/v1 Lease renewTime is RFC3339 MicroTime —
    client-go holders cannot parse an epoch float."""
    import datetime
    dt = datetime.datetime.fromtimestamp(ts, tz=datetime.timezone.utc)
    return dt.strftime('%Y-%m-%dT%H:%M:%S.%f') + 'Z'


def _parse_microtime(value) -> float:
    """Accept both RFC3339 MicroTime and the legacy epoch-float form."""
    if value is None or value == '':
        return 0.0
    try:
        return float(value)
    except (TypeError, ValueError):
        pass
    import datetime
    try:
        dt = datetime.datetime.strptime(str(value),
                                        '%Y-%m-%dT%H:%M:%S.%fZ')
        return dt.replace(tzinfo=datetime.timezone.utc).timestamp()
    except ValueError:
        try:
            dt = datetime.datetime.strptime(str(value),
                                            '%Y-%m-%dT%H:%M:%SZ')
            return dt.replace(tzinfo=datetime.timezone.utc).timestamp()
        except ValueError:
            return 0.0


def mesh_is_leader() -> bool:
    """Process 0 of the jax.distributed group leads (single-process
    setups are trivially the leader)."""
    try:
        import jax
        return jax.process_index() == 0
    except Exception:  # noqa: BLE001 - jax not initialized → standalone
        return True


class LeaderElector:
    """Lease-based election over the dynamic client."""

    def __init__(self, client, name: str, namespace: str = 'kyverno',
                 identity: Optional[str] = None,
                 on_started: Optional[Callable[[], None]] = None,
                 on_stopped: Optional[Callable[[], None]] = None):
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity or f'kyverno-{uuid.uuid4().hex[:8]}'
        self.on_started = on_started
        self.on_stopped = on_stopped
        self._leading = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def is_leader(self) -> bool:
        return self._leading

    def try_acquire(self, now: Optional[float] = None) -> bool:
        """One acquire/renew attempt; returns leadership state.

        The claim is a compare-and-swap: the update carries the observed
        ``resourceVersion``, so two replicas racing on an expired lease
        cannot both win — the loser's update conflicts (409) and it
        re-reads before deciding (client-go LeaderElector semantics)."""
        now = now or time.time()
        for _attempt in range(3):
            lease = None
            try:
                lease = self.client.get_resource(
                    'coordination.k8s.io/v1', 'Lease', self.namespace,
                    self.name)
            except Exception:  # noqa: BLE001
                lease = None
            if lease is None:
                try:
                    self.client.create_resource(
                        'coordination.k8s.io/v1', 'Lease', self.namespace, {
                            'apiVersion': 'coordination.k8s.io/v1',
                            'kind': 'Lease',
                            'metadata': {'name': self.name,
                                         'namespace': self.namespace},
                            'spec': {
                                'holderIdentity': self.identity,
                                'renewTime': _to_microtime(now),
                                'leaseDurationSeconds':
                                    int(LEASE_DURATION)}})
                except Exception:  # noqa: BLE001 - lost the create race
                    continue
                self._set_leading(True)
                return True
            spec = lease.setdefault('spec', {})
            holder = spec.get('holderIdentity', '')
            renew = _parse_microtime(spec.get('renewTime'))
            expired = now - renew > LEASE_DURATION
            if not (holder == self.identity or expired or not holder):
                self._set_leading(False)
                return False
            spec['holderIdentity'] = self.identity
            spec['renewTime'] = _to_microtime(now)
            try:
                # the lease still carries the resourceVersion we read —
                # a concurrent claimant makes this raise, and we re-read
                self.client.update_resource(
                    'coordination.k8s.io/v1', 'Lease', self.namespace,
                    lease)
            except Exception:  # noqa: BLE001 - conflict: re-observe
                continue
            self._set_leading(True)
            return True
        self._set_leading(False)
        return False

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading and self.on_started is not None:
            self.on_started()
        if not leading and self._leading and self.on_stopped is not None:
            self.on_stopped()
        self._leading = leading

    def run(self) -> None:
        def loop():
            while not self._stop.wait(RETRY_PERIOD):
                try:
                    self.try_acquire()
                except Exception:  # noqa: BLE001
                    self._set_leading(False)
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def release(self) -> None:
        """Graceful shutdown releases the lease
        (reference: pkg/webhooks/server.go:213 cleanup)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._leading:
            try:
                lease = self.client.get_resource(
                    'coordination.k8s.io/v1', 'Lease', self.namespace,
                    self.name)
                if (lease.get('spec') or {}).get(
                        'holderIdentity') == self.identity:
                    lease['spec']['holderIdentity'] = ''
                    self.client.update_resource(
                        'coordination.k8s.io/v1', 'Lease', self.namespace,
                        lease)
            except Exception:  # noqa: BLE001
                pass
        self._set_leading(False)
