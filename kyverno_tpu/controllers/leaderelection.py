"""Leader election.

Two modes, mirroring SURVEY §2.6's mapping of the reference's
client-go lease election (reference: pkg/leaderelection/
leaderelection.go:51 New):

* **Lease mode** — lease CRs through the dynamic client, for running
  multiple replicas against a shared API server like the reference.
* **Mesh mode** — under ``jax.distributed`` the leader is process 0 of
  the initialized process group: a single deterministic leader per
  slice with no extra coordination traffic (the TPU-native equivalent
  of one elected replica driving the reconcilers).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Optional

LEASE_DURATION = 15.0   # reference: leaderelection.go LeaseDuration
RENEW_DEADLINE = 10.0
RETRY_PERIOD = 2.0


def mesh_is_leader() -> bool:
    """Process 0 of the jax.distributed group leads (single-process
    setups are trivially the leader)."""
    try:
        import jax
        return jax.process_index() == 0
    except Exception:  # noqa: BLE001 - jax not initialized → standalone
        return True


class LeaderElector:
    """Lease-based election over the dynamic client."""

    def __init__(self, client, name: str, namespace: str = 'kyverno',
                 identity: Optional[str] = None,
                 on_started: Optional[Callable[[], None]] = None,
                 on_stopped: Optional[Callable[[], None]] = None):
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity or f'kyverno-{uuid.uuid4().hex[:8]}'
        self.on_started = on_started
        self.on_stopped = on_stopped
        self._leading = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def is_leader(self) -> bool:
        return self._leading

    def try_acquire(self, now: Optional[float] = None) -> bool:
        """One acquire/renew attempt; returns leadership state."""
        now = now or time.time()
        lease = None
        try:
            lease = self.client.get_resource(
                'coordination.k8s.io/v1', 'Lease', self.namespace,
                self.name)
        except Exception:  # noqa: BLE001
            lease = None
        if lease is None:
            self.client.create_resource(
                'coordination.k8s.io/v1', 'Lease', self.namespace, {
                    'apiVersion': 'coordination.k8s.io/v1', 'kind': 'Lease',
                    'metadata': {'name': self.name,
                                 'namespace': self.namespace},
                    'spec': {'holderIdentity': self.identity,
                             'renewTime': now,
                             'leaseDurationSeconds': int(LEASE_DURATION)}})
            self._set_leading(True)
            return True
        spec = lease.setdefault('spec', {})
        holder = spec.get('holderIdentity', '')
        renew = float(spec.get('renewTime') or 0)
        expired = now - renew > LEASE_DURATION
        if holder == self.identity or expired or not holder:
            spec['holderIdentity'] = self.identity
            spec['renewTime'] = now
            self.client.update_resource(
                'coordination.k8s.io/v1', 'Lease', self.namespace, lease)
            self._set_leading(True)
            return True
        self._set_leading(False)
        return False

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading and self.on_started is not None:
            self.on_started()
        if not leading and self._leading and self.on_stopped is not None:
            self.on_stopped()
        self._leading = leading

    def run(self) -> None:
        def loop():
            while not self._stop.wait(RETRY_PERIOD):
                try:
                    self.try_acquire()
                except Exception:  # noqa: BLE001
                    self._set_leading(False)
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def release(self) -> None:
        """Graceful shutdown releases the lease
        (reference: pkg/webhooks/server.go:213 cleanup)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._leading:
            try:
                lease = self.client.get_resource(
                    'coordination.k8s.io/v1', 'Lease', self.namespace,
                    self.name)
                if (lease.get('spec') or {}).get(
                        'holderIdentity') == self.identity:
                    lease['spec']['holderIdentity'] = ''
                    self.client.update_resource(
                        'coordination.k8s.io/v1', 'Lease', self.namespace,
                        lease)
            except Exception:  # noqa: BLE001
                pass
        self._set_leading(False)
