"""Async controllers (L5): webhook configuration reconciler, cert
manager, cleanup, leader election (reference: pkg/controllers)."""
