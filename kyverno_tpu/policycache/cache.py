"""Policy cache (reference: pkg/policycache/{cache,store,type}.go).

Indexes policies by (PolicyType, kind, namespace) so the admission hot
path resolves the applicable policy set with two dictionary lookups
instead of scanning every policy. Additionally keyed on the compiled
TPU artifact: the cache invalidation hook is where the batch evaluator's
compiled-program table gets rebuilt on policy change.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set

from ..api.policy import Policy, Rule
from ..api.unstructured import get_kind_from_gvk, split_subresource
from ..autogen.autogen import compute_rules
from ..utils.wildcard import check_patterns

# PolicyType (reference: pkg/policycache/type.go)
MUTATE = 'Mutate'
VALIDATE_ENFORCE = 'ValidateEnforce'
VALIDATE_AUDIT = 'ValidateAudit'
GENERATE = 'Generate'
VERIFY_IMAGES_MUTATE = 'VerifyImagesMutate'
VERIFY_IMAGES_VALIDATE = 'VerifyImagesValidate'

_ALL_TYPES = (MUTATE, VALIDATE_ENFORCE, VALIDATE_AUDIT, GENERATE,
              VERIFY_IMAGES_MUTATE, VERIFY_IMAGES_VALIDATE)


def _compute_kind(gvk: str) -> str:
    """reference: store.go:70 computeKind"""
    _, k = get_kind_from_gvk(gvk)
    kind, _ = split_subresource(k)
    return kind


def _is_enforce(action) -> bool:
    """'Enforce' plus the deprecated lowercase 'enforce'
    (reference: api/kyverno/v1/spec_types.go:29 Enforce())."""
    return action in ('Enforce', 'enforce')


def _compute_enforce(policy: Policy) -> bool:
    """reference: store.go:76 computeEnforcePolicy"""
    if _is_enforce(policy.validation_failure_action):
        return True
    return any(_is_enforce(o.get('action'))
               for o in policy.validation_failure_action_overrides)


def _check_overrides(enforce: bool, ns: str, policy: Policy) -> bool:
    """reference: cache.go:78 checkValidationFailureActionOverrides"""
    action_enforce = _is_enforce(policy.validation_failure_action)
    overrides = policy.validation_failure_action_overrides
    if action_enforce != enforce and (not ns or not overrides):
        return False
    for override in overrides:
        override_enforce = _is_enforce(override.get('action'))
        if override_enforce != enforce and \
                check_patterns(override.get('namespaces') or [], ns):
            return False
    return True


class Cache:
    """reference: pkg/policycache/cache.go:9 Cache"""

    def __init__(self,
                 on_change: Optional[Callable[[], None]] = None):
        self._lock = threading.RLock()
        self._policies: Dict[str, Policy] = {}
        # kind -> PolicyType -> set of policy keys
        self._kind_type: Dict[str, Dict[str, Set[str]]] = {}
        self._on_change = on_change

    # -- writes --------------------------------------------------------------

    def set(self, key: str, policy: Policy) -> None:
        """reference: store.go:95 policyMap.set"""
        with self._lock:
            self._unset_locked(key)
            self._policies[key] = policy
            enforce = _compute_enforce(policy)
            kind_states: Dict[str, dict] = {}
            for raw_rule in compute_rules(policy):
                rule = Rule(raw_rule)
                for gvk in self._match_kinds(rule):
                    kind = _compute_kind(gvk)
                    entry = kind_states.setdefault(kind, {
                        'mutate': False, 'validate': False,
                        'generate': False, 'verify_images': False,
                        'verify_images_validate': False})
                    entry['mutate'] |= rule.has_mutate()
                    entry['validate'] |= rule.has_validate()
                    entry['generate'] |= rule.has_generate()
                    entry['verify_images'] |= rule.has_verify_images()
                    entry['verify_images_validate'] |= any(
                        iv.get('verifyDigest', True) or
                        iv.get('required', True)
                        for iv in rule.verify_images)
            for kind, state in kind_states.items():
                buckets = self._kind_type.setdefault(
                    kind, {t: set() for t in _ALL_TYPES})
                self._apply(buckets[MUTATE], key, state['mutate'])
                self._apply(buckets[VALIDATE_ENFORCE], key,
                            state['validate'] and enforce)
                self._apply(buckets[VALIDATE_AUDIT], key,
                            state['validate'] and not enforce)
                self._apply(buckets[GENERATE], key, state['generate'])
                self._apply(buckets[VERIFY_IMAGES_MUTATE], key,
                            state['verify_images'])
                self._apply(buckets[VERIFY_IMAGES_VALIDATE], key,
                            state['verify_images'] and
                            state['verify_images_validate'])
        if self._on_change:
            self._on_change()

    @staticmethod
    def _match_kinds(rule: Rule) -> List[str]:
        # match-block kinds only (reference store.go:101 iterates
        # rule.MatchResources.GetKinds()); exclude kinds never index
        kinds: List[str] = []
        block = rule.match
        res = block.get('resources') or {}
        kinds.extend(res.get('kinds') or [])
        for f in (block.get('any') or []) + (block.get('all') or []):
            kinds.extend((f.get('resources') or {}).get('kinds') or [])
        return kinds

    @staticmethod
    def _apply(bucket: Set[str], key: str, value: bool) -> None:
        if value:
            bucket.add(key)
        else:
            bucket.discard(key)

    def unset(self, key: str) -> None:
        with self._lock:
            self._unset_locked(key)
        if self._on_change:
            self._on_change()

    def _unset_locked(self, key: str) -> None:
        self._policies.pop(key, None)
        for buckets in self._kind_type.values():
            for bucket in buckets.values():
                bucket.discard(key)

    # -- reads ---------------------------------------------------------------

    def get_policies(self, policy_type: str, kind: str,
                     namespace: str = '') -> List[Policy]:
        """reference: cache.go:38 GetPolicies"""
        with self._lock:
            result = self._get(policy_type, kind, '')
            result += self._get(policy_type, '*', '')
            if namespace:
                result += self._get(policy_type, kind, namespace)
                result += self._get(policy_type, '*', namespace)
            if policy_type == VALIDATE_AUDIT:
                result += self._get(VALIDATE_ENFORCE, kind, '')
                result += self._get(VALIDATE_ENFORCE, '*', '')
        if policy_type in (VALIDATE_AUDIT, VALIDATE_ENFORCE):
            enforce = policy_type == VALIDATE_ENFORCE
            result = [p for p in result
                      if _check_overrides(enforce, namespace, p)]
        return result

    def _get(self, policy_type: str, gvk: str, namespace: str
             ) -> List[Policy]:
        """reference: store.go:149 policyMap.get"""
        kind = _compute_kind(gvk)
        out = []
        for key in sorted(self._kind_type.get(kind, {})
                          .get(policy_type, ())):
            ns = key.split('/', 1)[0] if '/' in key else ''
            policy = self._policies.get(key)
            if policy is None:
                continue
            if not ns and not namespace:
                out.append(policy)
            elif ns == namespace:
                out.append(policy)
        return out

    def warm_up(self, policies: List[Policy]) -> None:
        """Bulk load; fires the recompile hook once, not per policy
        (reference: pkg/controllers/policycache/controller.go:133 WarmUp)."""
        hook, self._on_change = self._on_change, None
        try:
            for policy in policies:
                self.set(policy.get_kind_and_name(), policy)
        finally:
            self._on_change = hook
        if hook:
            hook()
