"""Type-indexed in-memory policy cache (reference: pkg/policycache)."""

from .cache import (  # noqa: F401
    GENERATE, MUTATE, VALIDATE_AUDIT, VALIDATE_ENFORCE,
    VERIFY_IMAGES_MUTATE, VERIFY_IMAGES_VALIDATE, Cache,
)
