"""Mock + protocol registry client (reference: pkg/registryclient/client.go).

The interface is the plugin boundary: ``fetch_image_descriptor`` resolves
a ref to its manifest digest; the cosign layer additionally reads the
signature/attestation payloads this store holds per image.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class RegistryError(Exception):
    """Registry access failure (maps to cosign rule-level errors)."""


class Descriptor:
    __slots__ = ('digest',)

    def __init__(self, digest: str):
        self.digest = digest


class MockRegistryClient:
    """In-memory registry: image ref (with or without tag/digest) →
    {digest, signatures: [keyid...], attestations: [in-toto statements]}.

    ``add_image`` registers an image; ``sign`` attaches signature key ids;
    ``attest`` attaches in-toto statements ({predicateType, predicate}).
    """

    def __init__(self):
        self._images: Dict[str, dict] = {}

    # -- setup ---------------------------------------------------------------

    def add_image(self, ref: str, digest: str) -> None:
        self._images[self._norm(ref)] = {
            'digest': digest, 'signatures': [], 'attestations': []}

    def sign(self, ref: str, key_id: str,
             subject: str = '', issuer: str = '') -> None:
        entry = self._entry(ref)
        entry['signatures'].append(
            {'key': key_id, 'subject': subject, 'issuer': issuer})

    def attest(self, ref: str, statement: dict,
               key_id: str = '') -> None:
        entry = self._entry(ref)
        entry['attestations'].append({'key': key_id, 'statement': statement})

    def add_signature(self, ref: str, entry: dict) -> None:
        """Attach a cryptographic signature entry (payload/signature[/cert])
        as produced by cosign.signature_entry."""
        self._entry(ref)['signatures'].append(entry)

    def add_attestation(self, ref: str, entry: dict) -> None:
        self._entry(ref)['attestations'].append(entry)

    # -- client interface ----------------------------------------------------

    def fetch_image_descriptor(self, ref: str) -> Descriptor:
        """reference: registryclient.Client.FetchImageDescriptor"""
        return Descriptor(self._entry(ref)['digest'])

    def get_signatures(self, ref: str) -> List[dict]:
        return list(self._entry(ref)['signatures'])

    def get_attestations(self, ref: str) -> List[dict]:
        return list(self._entry(ref)['attestations'])

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _norm(ref: str) -> str:
        # strip digest/tag so lookups by name, name:tag and name@digest all
        # resolve to the same entry
        if '@' in ref:
            ref = ref.split('@', 1)[0]
        last_slash = ref.rfind('/')
        colon = ref.rfind(':')
        if colon > last_slash:
            ref = ref[:colon]
        return ref

    def _entry(self, ref: str) -> dict:
        entry = self._images.get(self._norm(ref))
        if entry is None:
            raise RegistryError(f'image not found in registry: {ref}')
        return entry
