"""Registry client layer (reference: pkg/registryclient).

Network OCI access is environment-gated (the TPU build runs with zero
egress by default). ``MockRegistryClient`` is the hermetic store the CLI
and tests use — the same strategy as the reference CLI's registry mock
(cmd/cli/kubectl-kyverno/utils/store).
"""

from .client import (  # noqa: F401
    Descriptor, MockRegistryClient, RegistryError,
)
