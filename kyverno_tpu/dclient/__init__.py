"""Dynamic client layer (reference: pkg/clients/dclient).

The reference talks to a live Kubernetes API server through a dynamic
client plus discovery. The TPU-native framework keeps the same interface
as the plugin boundary but ships an in-memory fake (the reference's own
test strategy, pkg/clients/dclient/fake.go) as the default store; a real
cluster binding can be plugged in behind the same interface.
"""

from .client import (  # noqa: F401
    AlreadyExistsError,
    ApiError,
    FakeClient,
    NotFoundError,
)
