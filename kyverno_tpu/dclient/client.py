"""In-memory dynamic client (reference: pkg/clients/dclient/client.go:22,
fake.go).

Resources are stored unstructured, keyed by (apiVersion, kind, namespace,
name). Namespaces are themselves resources (v1/Namespace) so namespace
label lookups go through the same store. The client maintains
``resourceVersion`` counters the way the API server does, which the
generate controller's synchronize semantics depend on.
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..engine.match import check_selector


class ApiError(Exception):
    """Base API error (reference: k8s.io/apimachinery apierrors)."""

    reason = 'InternalError'


class NotFoundError(ApiError):
    reason = 'NotFound'


class ConflictError(ApiError):
    """Optimistic-concurrency failure: stale resourceVersion
    (HTTP 409 from a real API server)."""


class AlreadyExistsError(ApiError):
    reason = 'AlreadyExists'


Key = Tuple[str, str, str, str]


def _key(api_version: str, kind: str, namespace: str, name: str) -> Key:
    return (api_version or '', kind or '', namespace or '', name or '')


class FakeClient:
    """In-memory dclient.Interface (reference: pkg/clients/dclient/fake.go).

    Thread-safe: controllers run in worker threads the way the reference's
    workqueue workers do.
    """

    def __init__(self):
        self._store: Dict[Key, dict] = {}
        self._rv = 0
        self._lock = threading.RLock()
        # subscribers get (event_type, resource) for informer-style wiring
        self._watchers: List[Callable[[str, dict], None]] = []
        # SelfSubjectAccessReview policy: attrs -> (allowed, reason).
        # Defaults to allow-all, matching a kyverno install with the
        # shipped aggregated ClusterRoles in place.
        self.access_review_hook: Optional[
            Callable[[dict], Tuple[bool, str]]] = None

    # -- access review -------------------------------------------------------

    def create_access_review(self, attrs: dict) -> dict:
        """Create a SelfSubjectAccessReview; returns its status dict
        (reference: authorizationv1 SelfSubjectAccessReviews().Create,
        used by pkg/auth/auth.go:90)."""
        hook = self.access_review_hook
        if hook is None:
            return {'allowed': True}
        allowed, reason = hook(attrs)
        return {'allowed': bool(allowed), 'reason': reason}

    # -- watch ---------------------------------------------------------------

    def watch(self, fn: Callable[[str, dict], None]) -> None:
        """Register an informer-style event callback ('ADDED'/'MODIFIED'/
        'DELETED', resource)."""
        with self._lock:
            self._watchers.append(fn)

    def _notify(self, event: str, resource: dict) -> None:
        for fn in list(self._watchers):
            fn(event, copy.deepcopy(resource))

    # -- core verbs ----------------------------------------------------------

    def get_resource(self, api_version: str, kind: str, namespace: str,
                     name: str, subresource: str = '') -> dict:
        """reference: dclient.GetResource"""
        with self._lock:
            obj = self._store.get(_key(api_version, kind, namespace, name))
            if obj is None:
                obj = self._lookup_any_version(api_version, kind, namespace, name)
            if obj is None:
                raise NotFoundError(
                    f'{kind} "{namespace + "/" if namespace else ""}{name}" not found')
            if subresource:
                sub = obj.get(subresource)
                return copy.deepcopy(sub) if sub is not None else {}
            return copy.deepcopy(obj)

    def _lookup_any_version(self, api_version: str, kind: str,
                            namespace: str, name: str) -> Optional[dict]:
        # discovery fallback: empty apiVersion matches any stored version
        if api_version:
            return None
        for (av, k, ns, n), obj in self._store.items():
            if k == kind and ns == (namespace or '') and n == name:
                return obj
        return None

    def create_resource(self, api_version: str, kind: str, namespace: str,
                        resource: dict, dry_run: bool = False) -> dict:
        """reference: dclient.CreateResource"""
        obj = copy.deepcopy(resource)
        meta = obj.setdefault('metadata', {})
        name = meta.get('name', '')
        ns = meta.get('namespace', namespace or '')
        if namespace and not meta.get('namespace') and kind != 'Namespace':
            meta['namespace'] = namespace
            ns = namespace
        obj.setdefault('apiVersion', api_version)
        obj.setdefault('kind', kind)
        if obj['kind'] == 'Namespace':
            # the API server stamps this immutable label on every
            # namespace (k8s NamespaceDefaultLabelName); policies rely
            # on it for namespaceSelector matching
            meta.setdefault('labels', {}).setdefault(
                'kubernetes.io/metadata.name', name)
        if obj['kind'] == 'Secret' and obj.get('stringData'):
            # the API server folds stringData into base64 data on write
            import base64 as _b64
            data = obj.setdefault('data', {})
            for k, v in obj.pop('stringData').items():
                data[k] = _b64.b64encode(str(v).encode()).decode()
        key = _key(obj['apiVersion'], obj['kind'], ns if kind != 'Namespace' else '', name)
        with self._lock:
            if key in self._store:
                raise AlreadyExistsError(f'{kind} "{name}" already exists')
            if dry_run:
                return obj
            self._rv += 1
            meta['resourceVersion'] = str(self._rv)
            # the API server assigns the uid on create
            meta.setdefault('uid', f'uid-{self._rv}')
            self._store[key] = obj
            out = copy.deepcopy(obj)
        self._notify('ADDED', obj)
        return out

    def update_resource(self, api_version: str, kind: str, namespace: str,
                        resource: dict, dry_run: bool = False,
                        subresource: str = '') -> dict:
        """reference: dclient.UpdateResource / UpdateStatusResource"""
        obj = copy.deepcopy(resource)
        meta = obj.setdefault('metadata', {})
        name = meta.get('name', '')
        ns = namespace if kind != 'Namespace' else ''
        key = _key(api_version or obj.get('apiVersion', ''),
                   kind or obj.get('kind', ''), ns or meta.get('namespace', ''), name)
        with self._lock:
            if key not in self._store:
                raise NotFoundError(f'{kind} "{name}" not found')
            # optimistic concurrency: an update carrying a stale
            # resourceVersion is rejected like a real API server's 409
            sent_rv = meta.get('resourceVersion')
            stored_rv = (self._store[key].get('metadata') or {}).get(
                'resourceVersion')
            if sent_rv is not None and stored_rv is not None and \
                    sent_rv != stored_rv:
                raise ConflictError(
                    f'{kind} "{name}": resourceVersion conflict '
                    f'(sent {sent_rv}, current {stored_rv})')
            if dry_run:
                return obj
            self._rv += 1
            meta['resourceVersion'] = str(self._rv)
            obj.setdefault('apiVersion', api_version)
            obj.setdefault('kind', kind)
            self._store[key] = obj
            out = copy.deepcopy(obj)
        self._notify('MODIFIED', obj)
        return out

    def update_status_resource(self, api_version: str, kind: str,
                               namespace: str, resource: dict,
                               dry_run: bool = False) -> dict:
        return self.update_resource(api_version, kind, namespace, resource,
                                    dry_run, subresource='status')

    def delete_resource(self, api_version: str, kind: str, namespace: str,
                        name: str, dry_run: bool = False) -> None:
        """reference: dclient.DeleteResource"""
        with self._lock:
            key = _key(api_version, kind, namespace if kind != 'Namespace' else '', name)
            obj = self._store.get(key)
            if obj is None and not api_version:
                obj = self._lookup_any_version('', kind, namespace, name)
                if obj is not None:
                    key = _key(obj.get('apiVersion', ''), kind,
                               namespace if kind != 'Namespace' else '', name)
            if obj is None:
                raise NotFoundError(f'{kind} "{name}" not found')
            if dry_run:
                return
            del self._store[key]
        self._notify('DELETED', obj)

    def list_resource(self, api_version: str, kind: str, namespace: str = '',
                      selector: Optional[dict] = None) -> List[dict]:
        """reference: dclient.ListResource (label selector honored)."""
        out = []
        with self._lock:
            items = list(self._store.values())
        for obj in items:
            if kind and obj.get('kind') != kind:
                continue
            if api_version and obj.get('apiVersion') != api_version:
                continue
            meta = obj.get('metadata') or {}
            if namespace and meta.get('namespace', '') != namespace:
                continue
            if selector is not None:
                labels = {str(k): str(v)
                          for k, v in (meta.get('labels') or {}).items()}
                if not check_selector(selector, labels):
                    continue
            out.append(copy.deepcopy(obj))
        out.sort(key=lambda o: ((o.get('metadata') or {}).get('namespace', ''),
                                (o.get('metadata') or {}).get('name', '')))
        return out

    # -- raw REST access -----------------------------------------------------

    def raw_abs_path(self, path: str) -> bytes:
        """Serve a GET of a Kubernetes REST path from the store — the
        fake analogue of dclient.RawAbsPath (client.go:22), which the
        engine's APICall context entries use."""
        import json
        import re
        from urllib.parse import parse_qs, urlsplit
        split = urlsplit(path)
        p = split.path
        m = re.fullmatch(
            r'/(?:api/(?P<core>v1)|apis/(?P<group>[^/]+/[^/]+))'
            r'(?:/namespaces/(?P<ns>[^/]+))?'
            r'/(?P<plural>[^/?]+)'
            r'(?:/(?P<name>[^/?]+))?', p)
        if not m:
            raise NotFoundError(f'path {path!r} not found')
        av = m.group('core') or m.group('group')
        kind = self._kind_for_plural(m.group('plural'))
        if kind is None:
            raise NotFoundError(f'resource {m.group("plural")!r} unknown')
        ns = m.group('ns') or ''
        name = m.group('name') or ''
        if name:
            obj = self.get_resource(av, kind, ns, name)
            return json.dumps(obj).encode()
        selector = None
        sel = {k: v[0] for k, v in parse_qs(split.query).items()}.get(
            'labelSelector', '')
        if sel:
            from .fakeserver import _selector_from_query
            selector = _selector_from_query(sel)
        items = self.list_resource(av, kind, ns, selector)
        return json.dumps({'kind': f'{kind}List', 'apiVersion': av,
                           'items': items}).encode()

    _WELL_KNOWN_PLURALS = {
        'pods': 'Pod', 'namespaces': 'Namespace',
        'configmaps': 'ConfigMap', 'secrets': 'Secret',
        'services': 'Service', 'deployments': 'Deployment',
        'networkpolicies': 'NetworkPolicy',
        'clusterpolicies': 'ClusterPolicy', 'policies': 'Policy',
        'updaterequests': 'UpdateRequest',
        'policyreports': 'PolicyReport',
        'clusterpolicyreports': 'ClusterPolicyReport',
    }

    def _kind_for_plural(self, plural: str) -> Optional[str]:
        kind = self._WELL_KNOWN_PLURALS.get(plural)
        if kind:
            return kind
        # fall back to naive pluralization over stored kinds
        with self._lock:
            kinds = {k for (_av, k, _ns, _n) in self._store}
        for k in kinds:
            low = k.lower()
            if plural in (low + 's', low + 'es',
                          low[:-1] + 'ies' if low.endswith('y') else ''):
                return k
        return None

    # -- namespace helpers ---------------------------------------------------

    def get_namespace_labels(self, namespace: str) -> Dict[str, str]:
        """Namespace labels for match-time `namespaceSelector` evaluation
        (reference: pkg/utils/kube GetNamespaceSelectorsFromNamespaceLister)."""
        try:
            ns = self.get_resource('v1', 'Namespace', '', namespace)
        except NotFoundError:
            return {}
        labels = (ns.get('metadata') or {}).get('labels') or {}
        return {str(k): str(v) for k, v in labels.items()}
