"""Real cluster transport behind the dclient interface (reference:
pkg/clients/dclient/client.go:22 — the dynamic client + discovery the
reference builds over client-go).

``HTTPClient`` speaks the Kubernetes REST API over stdlib
``http.client`` (the hermetic image has no kubernetes pip package, and
the runtime surface needed is small): kubeconfig loading with token /
client-certificate auth and cluster CA trust, kind→resource discovery
via ``/api`` + ``/apis`` APIResourceLists, the CRUD verbs with API
``Status`` errors mapped onto the :mod:`client` ApiError taxonomy, JSON
``PATCH``, label-selector LIST, and streaming WATCH.

``FakeClient`` and ``HTTPClient`` pass one shared contract-test suite
(tests/test_dclient_contract.py) — the fake API server there wraps a
``FakeClient`` store, so the transport mapping is exercised end to end.
"""

from __future__ import annotations

import base64
import json
import ssl
import tempfile
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import quote, urlencode, urlsplit

from .client import (AlreadyExistsError, ApiError, ConflictError,
                     NotFoundError)
from ..engine.match import check_selector


class ForbiddenError(ApiError):
    reason = 'Forbidden'


class BadRequestError(ApiError):
    reason = 'BadRequest'


_REASON_ERRORS = {
    'NotFound': NotFoundError,
    'AlreadyExists': AlreadyExistsError,
    'Conflict': ConflictError,
    'Forbidden': ForbiddenError,
    'BadRequest': BadRequestError,
}

_CODE_ERRORS = {
    400: BadRequestError,
    403: ForbiddenError,
    404: NotFoundError,
    409: ConflictError,
}


def error_from_status(code: int, body: bytes) -> ApiError:
    """Map an API ``Status`` response onto the ApiError taxonomy the
    in-memory client raises (apimachinery reasons win over HTTP codes:
    409 covers both AlreadyExists and Conflict)."""
    message = ''
    reason = ''
    try:
        doc = json.loads(body)
        message = doc.get('message', '')
        reason = doc.get('reason', '')
    except ValueError:
        message = body.decode('utf-8', 'replace')[:200]
    cls = _REASON_ERRORS.get(reason) or _CODE_ERRORS.get(code, ApiError)
    return cls(message or f'HTTP {code}')


class ClusterConfig:
    """Connection parameters resolved from a kubeconfig context."""

    __slots__ = ('server', 'ca_data', 'token', 'client_cert_data',
                 'client_key_data', 'insecure')

    def __init__(self, server: str, ca_data: bytes = b'', token: str = '',
                 client_cert_data: bytes = b'', client_key_data: bytes = b'',
                 insecure: bool = False):
        self.server = server
        self.ca_data = ca_data
        self.token = token
        self.client_cert_data = client_cert_data
        self.client_key_data = client_key_data
        self.insecure = insecure


def _file_or_data(section: dict, key: str) -> bytes:
    """kubeconfig fields come as either ``<key>-data`` (base64 inline)
    or ``<key>`` (a file path)."""
    data = section.get(f'{key}-data')
    if data:
        return base64.b64decode(data)
    path = section.get(key)
    if path:
        with open(path, 'rb') as f:
            return f.read()
    return b''


def load_kubeconfig(path: str, context: str = '') -> ClusterConfig:
    """Resolve (cluster, user) for ``context`` (default: current-context)
    from a kubeconfig file (client-go clientcmd semantics for the fields
    the transport needs)."""
    import yaml
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    ctx_name = context or doc.get('current-context', '')
    contexts = {c.get('name'): c.get('context') or {}
                for c in doc.get('contexts') or []}
    if ctx_name not in contexts:
        raise ApiError(f'kubeconfig context {ctx_name!r} not found')
    ctx = contexts[ctx_name]
    clusters = {c.get('name'): c.get('cluster') or {}
                for c in doc.get('clusters') or []}
    users = {u.get('name'): u.get('user') or {}
             for u in doc.get('users') or []}
    cluster = clusters.get(ctx.get('cluster'))
    if cluster is None:
        raise ApiError(f'kubeconfig cluster {ctx.get("cluster")!r} not found')
    user = users.get(ctx.get('user')) or {}
    token = user.get('token', '')
    if not token and user.get('tokenFile'):
        with open(user['tokenFile']) as f:
            token = f.read().strip()
    return ClusterConfig(
        server=cluster.get('server', ''),
        ca_data=_file_or_data(cluster, 'certificate-authority'),
        token=token,
        client_cert_data=_file_or_data(user, 'client-certificate'),
        client_key_data=_file_or_data(user, 'client-key'),
        insecure=bool(cluster.get('insecure-skip-tls-verify')),
    )


class HTTPClient:
    """dclient.Interface over the Kubernetes REST API."""

    def __init__(self, config: ClusterConfig, timeout: float = 30.0):
        self.config = config
        self.timeout = timeout
        split = urlsplit(config.server)
        self._scheme = split.scheme or 'https'
        self._host = split.hostname or 'localhost'
        self._port = split.port or (443 if self._scheme == 'https' else 80)
        self._base_path = split.path.rstrip('/')
        self._ssl_ctx = self._build_ssl() if self._scheme == 'https' else None
        # (api_version, kind) -> (plural, namespaced)
        self._discovery: Dict[Tuple[str, str], Tuple[str, bool]] = {}
        self._discovery_lock = threading.Lock()
        self._watch_stop = threading.Event()

    # -- connection --------------------------------------------------------

    def _build_ssl(self) -> ssl.SSLContext:
        ctx = ssl.create_default_context()
        if self.config.insecure:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.config.ca_data:
            ctx.load_verify_locations(
                cadata=self.config.ca_data.decode('utf-8', 'replace'))
        if self.config.client_cert_data and self.config.client_key_data:
            # ssl wants files; keep them for the context's lifetime
            self._certfile = tempfile.NamedTemporaryFile(suffix='.pem')
            self._certfile.write(self.config.client_cert_data)
            self._certfile.flush()
            self._keyfile = tempfile.NamedTemporaryFile(suffix='.pem')
            self._keyfile.write(self.config.client_key_data)
            self._keyfile.flush()
            ctx.load_cert_chain(self._certfile.name, self._keyfile.name)
        return ctx

    def _connect(self):
        import http.client
        if self._scheme == 'https':
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=self.timeout,
                context=self._ssl_ctx)
        return http.client.HTTPConnection(self._host, self._port,
                                          timeout=self.timeout)

    def _request(self, method: str, path: str, body: Optional[bytes] = None,
                 content_type: str = 'application/json') -> bytes:
        conn = self._connect()
        try:
            headers = {'Accept': 'application/json'}
            if self.config.token:
                headers['Authorization'] = f'Bearer {self.config.token}'
            if body is not None:
                headers['Content-Type'] = content_type
            conn.request(method, self._base_path + path, body=body,
                         headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                raise error_from_status(resp.status, data)
            return data
        finally:
            conn.close()

    def raw_abs_path(self, path: str) -> bytes:
        """reference: dclient.RawAbsPath — APICall context entries."""
        return self._request('GET', path)

    # -- discovery ---------------------------------------------------------

    def _resource_info(self, api_version: str, kind: str
                       ) -> Tuple[str, bool]:
        key = (api_version, kind)
        with self._discovery_lock:
            hit = self._discovery.get(key)
        if hit is not None:
            return hit
        group_path = f'/api/{api_version}' if '/' not in api_version \
            else f'/apis/{api_version}'
        try:
            doc = json.loads(self._request('GET', group_path))
        except ApiError:
            doc = {}
        found: Optional[Tuple[str, bool]] = None
        with self._discovery_lock:
            for r in doc.get('resources') or []:
                if '/' in r.get('name', ''):
                    continue  # subresources
                info = (r['name'], bool(r.get('namespaced')))
                self._discovery[(api_version, r.get('kind', ''))] = info
                if r.get('kind') == kind:
                    found = info
            if found is None:
                # fallback pluralization for servers without discovery
                found = (_pluralize(kind), kind != 'Namespace')
                self._discovery[key] = found
            return found

    def _path(self, api_version: str, kind: str, namespace: str,
              name: str = '', subresource: str = '',
              query: Optional[Dict[str, str]] = None) -> str:
        plural, namespaced = self._resource_info(api_version, kind)
        root = f'/api/{api_version}' if '/' not in api_version \
            else f'/apis/{api_version}'
        parts = [root]
        if namespaced and namespace:
            parts.append(f'namespaces/{quote(namespace)}')
        parts.append(plural)
        if name:
            parts.append(quote(name))
        if subresource:
            parts.append(subresource)
        path = '/'.join(parts)
        if query:
            path += '?' + urlencode(query)
        return path

    # -- verbs -------------------------------------------------------------

    def get_resource(self, api_version: str, kind: str, namespace: str,
                     name: str, subresource: str = '') -> dict:
        api_version = api_version or self._guess_version(kind)
        data = self._request('GET', self._path(
            api_version, kind, namespace, name, subresource))
        return json.loads(data)

    def _guess_version(self, kind: str) -> str:
        with self._discovery_lock:
            for (av, k) in self._discovery:
                if k == kind:
                    return av
        return 'v1'

    def create_resource(self, api_version: str, kind: str, namespace: str,
                        resource: dict, dry_run: bool = False) -> dict:
        query = {'dryRun': 'All'} if dry_run else None
        obj = dict(resource)
        obj.setdefault('apiVersion', api_version)
        obj.setdefault('kind', kind)
        data = self._request('POST', self._path(
            api_version, kind,
            namespace or (obj.get('metadata') or {}).get('namespace', ''),
            query=query), json.dumps(obj).encode())
        return json.loads(data)

    def update_resource(self, api_version: str, kind: str, namespace: str,
                        resource: dict, dry_run: bool = False,
                        subresource: str = '') -> dict:
        meta = resource.get('metadata') or {}
        query = {'dryRun': 'All'} if dry_run else None
        obj = dict(resource)
        obj.setdefault('apiVersion', api_version)
        obj.setdefault('kind', kind)
        data = self._request('PUT', self._path(
            api_version, kind,
            namespace or meta.get('namespace', ''), meta.get('name', ''),
            subresource, query=query), json.dumps(obj).encode())
        return json.loads(data)

    def update_status_resource(self, api_version: str, kind: str,
                               namespace: str, resource: dict,
                               dry_run: bool = False) -> dict:
        return self.update_resource(api_version, kind, namespace, resource,
                                    dry_run, subresource='status')

    def create_access_review(self, attrs: dict) -> dict:
        """POST a SelfSubjectAccessReview; returns its status dict
        (reference: pkg/auth/auth.go:90 ssarClient.Create)."""
        ssar = {
            'apiVersion': 'authorization.k8s.io/v1',
            'kind': 'SelfSubjectAccessReview',
            'spec': {'resourceAttributes': attrs},
        }
        data = self._request(
            'POST', '/apis/authorization.k8s.io/v1/selfsubjectaccessreviews',
            json.dumps(ssar).encode())
        return (json.loads(data).get('status') or {})

    def patch_resource(self, api_version: str, kind: str, namespace: str,
                       name: str, patch: List[dict]) -> dict:
        """reference: dclient.PatchResource (RFC 6902 JSON patch)."""
        data = self._request(
            'PATCH', self._path(api_version, kind, namespace, name),
            json.dumps(patch).encode(),
            content_type='application/json-patch+json')
        return json.loads(data)

    def delete_resource(self, api_version: str, kind: str, namespace: str,
                        name: str, dry_run: bool = False) -> None:
        query = {'dryRun': 'All'} if dry_run else None
        self._request('DELETE', self._path(
            api_version, kind, namespace, name, query=query))

    def list_resource(self, api_version: str, kind: str, namespace: str = '',
                      selector: Optional[dict] = None) -> List[dict]:
        query: Dict[str, str] = {}
        sel = _selector_string(selector)
        if sel:
            query['labelSelector'] = sel
        data = self._request('GET', self._path(
            api_version, kind, namespace, query=query or None))
        doc = json.loads(data)
        items = doc.get('items') or []
        if selector is not None and not sel:
            # matchExpressions beyond the string form: filter client-side
            items = [o for o in items if check_selector(
                selector, {str(k): str(v) for k, v in
                           ((o.get('metadata') or {}).get('labels')
                            or {}).items()})]
        return items

    def get_namespace_labels(self, namespace: str) -> Dict[str, str]:
        try:
            ns = self.get_resource('v1', 'Namespace', '', namespace)
        except NotFoundError:
            return {}
        labels = (ns.get('metadata') or {}).get('labels') or {}
        return {str(k): str(v) for k, v in labels.items()}

    # -- watch -------------------------------------------------------------

    def watch(self, fn: Callable[[str, dict], None],
              api_version: str = 'v1', kind: str = '',
              namespace: str = '') -> threading.Thread:
        """Streaming WATCH on a background thread; events are delivered
        as (type, object) like the in-memory client's informer hook.
        Returns the thread; ``close()`` stops it."""

        def run():
            while not self._watch_stop.is_set():
                try:
                    self._watch_once(fn, api_version, kind, namespace)
                except (ApiError, OSError):
                    if self._watch_stop.wait(1.0):
                        return

        t = threading.Thread(target=run, daemon=True, name='dclient-watch')
        t.start()
        return t

    def _watch_once(self, fn, api_version, kind, namespace):
        conn = self._connect()
        try:
            headers = {'Accept': 'application/json'}
            if self.config.token:
                headers['Authorization'] = f'Bearer {self.config.token}'
            path = self._path(api_version, kind, namespace,
                              query={'watch': 'true'})
            conn.request('GET', self._base_path + path, headers=headers)
            resp = conn.getresponse()
            if resp.status >= 400:
                raise error_from_status(resp.status, resp.read())
            buf = b''
            while not self._watch_stop.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b'\n' in buf:
                    line, buf = buf.split(b'\n', 1)
                    if not line.strip():
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    fn(ev.get('type', ''), ev.get('object') or {})
        finally:
            conn.close()

    def close(self) -> None:
        self._watch_stop.set()


def _pluralize(kind: str) -> str:
    k = kind.lower()
    if k.endswith('y'):
        return k[:-1] + 'ies'
    if k.endswith(('s', 'x', 'z', 'ch', 'sh')):
        return k + 'es'
    return k + 's'


def _selector_string(selector: Optional[dict]) -> str:
    """matchLabels (+ In/NotIn/Exists/DoesNotExist expressions) as a
    labelSelector query string; richer expressions return '' and are
    filtered client-side."""
    if not selector:
        return ''
    parts = []
    for k, v in (selector.get('matchLabels') or {}).items():
        parts.append(f'{k}={v}')
    for expr in selector.get('matchExpressions') or []:
        op = (expr.get('operator') or '').lower()
        key = expr.get('key', '')
        values = ','.join(expr.get('values') or [])
        if op == 'in':
            parts.append(f'{key} in ({values})')
        elif op == 'notin':
            parts.append(f'{key} notin ({values})')
        elif op == 'exists':
            parts.append(key)
        elif op == 'doesnotexist':
            parts.append(f'!{key}')
        else:
            return ''
    return ','.join(parts)
