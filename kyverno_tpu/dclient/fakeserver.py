"""A minimal in-process Kubernetes API server for transport testing.

Wraps a :class:`FakeClient` store behind the REST surface
:class:`HTTPClient` speaks — discovery, CRUD, JSON patch, labelSelector
LIST, streaming WATCH — translating ApiErrors back into apimachinery
``Status`` bodies.  This is the "recorded-response fake server" of the
dclient contract suite: both clients run the same tests, one directly
against the store, one through real HTTP.
"""

from __future__ import annotations

import json
import queue
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from .client import (AlreadyExistsError, ApiError, ConflictError,
                     FakeClient, NotFoundError)

_STATUS_CODES = {
    'NotFound': 404,
    'AlreadyExists': 409,
    'Conflict': 409,
    'Forbidden': 403,
    'BadRequest': 400,
}


def _status_body(err: ApiError) -> bytes:
    reason = getattr(err, 'reason', '') or type(err).__name__.replace(
        'Error', '')
    if isinstance(err, ConflictError):
        reason = 'Conflict'
    return json.dumps({
        'kind': 'Status', 'apiVersion': 'v1', 'status': 'Failure',
        'message': str(err), 'reason': reason,
        'code': _STATUS_CODES.get(reason, 500),
    }).encode()


class _Registry:
    """kind↔plural registry; pre-seeded with the kinds the framework
    touches, extensible for tests."""

    def __init__(self):
        self.by_plural: Dict[Tuple[str, str], Tuple[str, bool]] = {}
        for api_version, kind, plural, namespaced in [
            ('v1', 'Pod', 'pods', True),
            ('v1', 'Namespace', 'namespaces', False),
            ('v1', 'ConfigMap', 'configmaps', True),
            ('v1', 'Secret', 'secrets', True),
            ('v1', 'Service', 'services', True),
            ('apps/v1', 'Deployment', 'deployments', True),
            ('networking.k8s.io/v1', 'NetworkPolicy', 'networkpolicies',
             True),
            ('kyverno.io/v1', 'ClusterPolicy', 'clusterpolicies', False),
            ('kyverno.io/v1beta1', 'UpdateRequest', 'updaterequests', True),
            ('wgpolicyk8s.io/v1alpha2', 'PolicyReport', 'policyreports',
             True),
        ]:
            self.register(api_version, kind, plural, namespaced)

    def register(self, api_version: str, kind: str, plural: str,
                 namespaced: bool) -> None:
        self.by_plural[(api_version, plural)] = (kind, namespaced)

    def discovery_doc(self, api_version: str) -> dict:
        resources = []
        for (av, plural), (kind, namespaced) in sorted(
                self.by_plural.items()):
            if av == api_version:
                resources.append({'name': plural, 'kind': kind,
                                  'namespaced': namespaced})
        return {'kind': 'APIResourceList', 'groupVersion': api_version,
                'resources': resources}


class FakeApiServer:
    """`with FakeApiServer() as srv:` — srv.url points at a live server
    backed by ``srv.store`` (a FakeClient)."""

    def __init__(self, store: Optional[FakeClient] = None):
        self.store = store or FakeClient()
        self.registry = _Registry()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, *a):  # noqa: D102 - quiet
                pass

            def _send(self, code: int, body: bytes,
                      content_type='application/json'):
                self.send_response(code)
                self.send_header('Content-Type', content_type)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _fail(self, err: ApiError):
                reason = 'Conflict' if isinstance(err, ConflictError) else \
                    getattr(err, 'reason', 'InternalError')
                self._send(_STATUS_CODES.get(reason, 500),
                           _status_body(err))

            def _route(self):
                split = urlsplit(self.path)
                q = {k: v[0] for k, v in parse_qs(split.query).items()}
                return split.path, q

            def do_GET(self):  # noqa: N802
                path, q = self._route()
                try:
                    m = re.fullmatch(r'/api/(v1)|/apis/([^/]+/[^/]+)', path)
                    if m:
                        av = m.group(1) or m.group(2)
                        self._send(200, json.dumps(
                            outer.registry.discovery_doc(av)).encode())
                        return
                    parsed = outer._parse(path)
                    if parsed is None:
                        raise NotFoundError(f'path {path!r} not found')
                    av, kind, ns, name = parsed
                    if q.get('watch') == 'true':
                        self._watch(av, kind, ns)
                        return
                    if name:
                        obj = outer.store.get_resource(av, kind, ns, name)
                        self._send(200, json.dumps(obj).encode())
                        return
                    selector = _selector_from_query(
                        q.get('labelSelector', ''))
                    items = outer.store.list_resource(av, kind, ns,
                                                      selector)
                    self._send(200, json.dumps({
                        'kind': f'{kind}List', 'apiVersion': av,
                        'items': items}).encode())
                except ApiError as e:
                    self._fail(e)

            def _watch(self, av, kind, ns):
                events: 'queue.Queue' = queue.Queue()

                def hook(ev_type, obj):
                    if kind and obj.get('kind') != kind:
                        return
                    if ns and (obj.get('metadata') or {}).get(
                            'namespace', '') != ns:
                        return
                    events.put((ev_type, obj))
                outer.store.watch(hook)
                self.send_response(200)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Transfer-Encoding', 'chunked')
                self.end_headers()
                try:
                    while True:
                        ev_type, obj = events.get(timeout=10)
                        line = json.dumps(
                            {'type': ev_type, 'object': obj}).encode() + \
                            b'\n'
                        self.wfile.write(
                            f'{len(line):x}\r\n'.encode() + line + b'\r\n')
                        self.wfile.flush()
                except (queue.Empty, OSError):
                    try:
                        self.wfile.write(b'0\r\n\r\n')
                    except OSError:
                        pass

            def _read_body(self) -> bytes:
                n = int(self.headers.get('Content-Length') or 0)
                return self.rfile.read(n)

            def do_POST(self):  # noqa: N802
                path, q = self._route()
                if path == ('/apis/authorization.k8s.io/v1/'
                            'selfsubjectaccessreviews'):
                    # SSARs are ephemeral: evaluated, never stored
                    # (reference: authorization/v1 SelfSubjectAccessReview)
                    ssar = json.loads(self._read_body())
                    attrs = ((ssar.get('spec') or {})
                             .get('resourceAttributes') or {})
                    status = outer.store.create_access_review(attrs)
                    ssar['status'] = status
                    self._send(201, json.dumps(ssar).encode())
                    return
                try:
                    parsed = outer._parse(path)
                    if parsed is None:
                        raise NotFoundError(f'path {path!r} not found')
                    av, kind, ns, _ = parsed
                    obj = json.loads(self._read_body())
                    out = outer.store.create_resource(
                        av, kind, ns, obj, dry_run=q.get('dryRun') == 'All')
                    self._send(201, json.dumps(out).encode())
                except ApiError as e:
                    self._fail(e)

            def do_PUT(self):  # noqa: N802
                path, q = self._route()
                try:
                    parsed = outer._parse(path)
                    if parsed is None:
                        raise NotFoundError(f'path {path!r} not found')
                    av, kind, ns, name = parsed
                    obj = json.loads(self._read_body())
                    out = outer.store.update_resource(
                        av, kind, ns, obj, dry_run=q.get('dryRun') == 'All')
                    self._send(200, json.dumps(out).encode())
                except ApiError as e:
                    self._fail(e)

            def do_PATCH(self):  # noqa: N802
                path, _q = self._route()
                try:
                    parsed = outer._parse(path)
                    if parsed is None:
                        raise NotFoundError(f'path {path!r} not found')
                    av, kind, ns, name = parsed
                    from ..engine.mutate.jsonpatch import apply_patch
                    current = outer.store.get_resource(av, kind, ns, name)
                    patched = apply_patch(
                        current, json.loads(self._read_body()))
                    out = outer.store.update_resource(av, kind, ns, patched)
                    self._send(200, json.dumps(out).encode())
                except ApiError as e:
                    self._fail(e)

            def do_DELETE(self):  # noqa: N802
                path, q = self._route()
                try:
                    parsed = outer._parse(path)
                    if parsed is None:
                        raise NotFoundError(f'path {path!r} not found')
                    av, kind, ns, name = parsed
                    outer.store.delete_resource(
                        av, kind, ns, name,
                        dry_run=q.get('dryRun') == 'All')
                    self._send(200, json.dumps({
                        'kind': 'Status', 'status': 'Success'}).encode())
                except ApiError as e:
                    self._fail(e)

        self._server = ThreadingHTTPServer(('127.0.0.1', 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name='fake-apiserver')

    def _parse(self, path: str
               ) -> Optional[Tuple[str, str, str, str]]:
        """(api_version, kind, namespace, name) from a REST path."""
        m = re.fullmatch(
            r'/(?:api/(?P<core>v1)|apis/(?P<group>[^/]+/[^/]+))'
            r'(?:/namespaces/(?P<ns>[^/]+))?'
            r'/(?P<plural>[^/?]+)'
            r'(?:/(?P<name>[^/?]+))?'
            r'(?:/status)?', path)
        if not m:
            return None
        av = m.group('core') or m.group('group')
        plural = m.group('plural')
        info = self.registry.by_plural.get((av, plural))
        if info is None:
            return None
        kind, _namespaced = info
        return av, kind, m.group('ns') or '', m.group('name') or ''

    @property
    def url(self) -> str:
        host, port = self._server.server_address
        return f'http://{host}:{port}'

    def __enter__(self) -> 'FakeApiServer':
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._server.shutdown()
        self._server.server_close()


def _selector_from_query(sel: str) -> Optional[dict]:
    """labelSelector query string → selector dict (the k=v and
    expression forms HTTPClient emits)."""
    if not sel:
        return None
    match_labels: Dict[str, str] = {}
    exprs = []
    for raw in re.split(r',(?![^(]*\))', sel):
        part = raw.strip()
        if not part:
            continue
        m = re.fullmatch(r'(\S+)\s+(in|notin)\s+\(([^)]*)\)', part)
        if m:
            exprs.append({'key': m.group(1),
                          'operator': 'In' if m.group(2) == 'in'
                          else 'NotIn',
                          'values': [v.strip()
                                     for v in m.group(3).split(',')]})
            continue
        if part.startswith('!'):
            exprs.append({'key': part[1:], 'operator': 'DoesNotExist'})
            continue
        if '=' in part:
            k, v = part.split('=', 1)
            match_labels[k.strip()] = v.strip().lstrip('=')
            continue
        exprs.append({'key': part, 'operator': 'Exists'})
    out: dict = {}
    if match_labels:
        out['matchLabels'] = match_labels
    if exprs:
        out['matchExpressions'] = exprs
    return out or None
