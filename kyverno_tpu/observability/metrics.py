"""Metrics instruments with Prometheus text exposition.

Mirrors the reference's OTel instrument set (reference:
pkg/metrics/metrics.go:91-224 — kyverno_policy_results_total,
kyverno_policy_execution_duration_seconds, kyverno_policy_changes_total,
kyverno_admission_review_duration_seconds, kyverno_client_queries_total)
without external dependencies: counters and histograms keyed by label
tuples, rendered in Prometheus text format for a /metrics endpoint.
Per-metric disable/relabel follows the dynamic metrics configuration
(reference: pkg/config/metricsconfig.go).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                    2.5, 5.0, 10.0)

#: overflow counter of the label-cardinality guard (KTPU_METRIC_SERIES_MAX)
SERIES_DROPPED = 'kyverno_tpu_metric_series_dropped_total'


def _series_max() -> int:
    try:
        return int(os.environ.get('KTPU_METRIC_SERIES_MAX', '512'))
    except ValueError:
        return 512

#: compile/scan-scale buckets: fresh-cache policy-set compiles measure
#: 43-49s (STATUS.md) — the default buckets top out at 10s and every
#: compile sample would land in +Inf
WIDE_BUCKETS = (0.005, 0.025, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0,
                30.0, 60.0, 120.0)


class MetricsRegistry:
    def __init__(self, disabled: Optional[List[str]] = None):
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[Tuple, float]] = {}
        self._gauges: Dict[str, Dict[Tuple, float]] = {}
        self._hists: Dict[str, Dict[Tuple, List]] = {}
        self._buckets: Dict[str, Tuple[float, ...]] = {}
        self._disabled = set(disabled or [])
        self._reset_on_close: set = set()
        # label-cardinality guard: per-host/per-shard labels under a
        # large fleet must not explode the registry, so a metric caps
        # out at this many distinct label-sets — existing series keep
        # updating, NEW series beyond the cap are refused and counted
        self._series_cap = _series_max()

    def _admit(self, store: Dict[str, Dict[Tuple, Any]], name: str,
               key: Tuple) -> bool:
        """Under ``self._lock``: may ``(name, key)`` gain a series?
        Overflow counts on the drop counter directly (bypassing the
        guard — its own cardinality is bounded by the catalog)."""
        series = store.get(name)
        if series is None or key in series or \
                len(series) < self._series_cap or name == SERIES_DROPPED:
            return True
        dropped = self._counters.setdefault(SERIES_DROPPED, {})
        dkey = (('metric', name),)
        dropped[dkey] = dropped.get(dkey, 0.0) + 1.0
        return False

    def mark_reset_on_close(self, name: str) -> None:
        """Mark ``name`` as a *residency* gauge: it describes live
        occupancy (queue depth, in-flight chunks, breaker states), so
        after a drain/shutdown its series must export 0, not whatever
        the last sample happened to be.  Swept by
        :meth:`reset_residency_gauges` (cmd/internal.Setup.shutdown)."""
        with self._lock:
            self._reset_on_close.add(name)

    def reset_residency_gauges(self) -> None:
        """Zero every series of every gauge marked reset_on_close.
        Series are zeroed, not retracted — 'scraped the drained server
        and saw 0' is the signal; a vanished series reads as target
        loss."""
        with self._lock:
            for name in self._reset_on_close:
                series = self._gauges.get(name)
                if series is not None:
                    for key in series:
                        series[key] = 0.0

    def register_histogram(self, name: str,
                           buckets: Tuple[float, ...]) -> None:
        """Per-histogram bucket override; must run before the first
        ``observe`` of ``name`` (bucket counters are sized on first
        sample)."""
        with self._lock:
            if name not in self._hists:
                self._buckets[name] = tuple(buckets)

    def configure(self, disabled: List[str]) -> None:
        with self._lock:
            self._disabled = set(disabled)

    def inc(self, name: str, amount: float = 1.0, **labels) -> None:
        if name in self._disabled:
            return
        key = tuple(sorted(labels.items()))
        with self._lock:
            if not self._admit(self._counters, name, key):
                return
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + amount

    def set_gauge(self, name: str, value: float, **labels) -> None:
        # zero is a legitimate gauge value (a scraped series vanishing
        # reads as "target gone", not "value is 0") — intentional
        # removal goes through clear_gauge
        if name in self._disabled:
            return
        key = tuple(sorted(labels.items()))
        with self._lock:
            if not self._admit(self._gauges, name, key):
                return
            self._gauges.setdefault(name, {})[key] = value

    def clear_gauge(self, name: str, **labels) -> None:
        """Drop one gauge series from exposition (retraction of a
        no-longer-existing label combination, e.g. a deleted rule)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            series = self._gauges.get(name)
            if series is not None:
                series.pop(key, None)

    def gauge_value(self, name: str, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._gauges.get(name, {}).get(key, 0.0)

    def gauge_total(self, name: str) -> float:
        with self._lock:
            return sum(self._gauges.get(name, {}).values())

    def observe(self, name: str, value: float, **labels) -> None:
        if name in self._disabled:
            return
        key = tuple(sorted(labels.items()))
        with self._lock:
            if not self._admit(self._hists, name, key):
                return
            bounds = self._buckets.get(name, _DEFAULT_BUCKETS)
            series = self._hists.setdefault(name, {})
            entry = series.get(key)
            if entry is None:
                entry = [0, 0.0, [0] * len(bounds)]
                series[key] = entry
            entry[0] += 1
            entry[1] += value
            for i, bound in enumerate(bounds):
                if value <= bound:
                    entry[2][i] += 1

    def histogram_sum(self, name: str, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            entry = self._hists.get(name, {}).get(key)
            return entry[1] if entry is not None else 0.0

    def histogram_count(self, name: str, **labels) -> int:
        key = tuple(sorted(labels.items()))
        with self._lock:
            entry = self._hists.get(name, {}).get(key)
            return entry[0] if entry is not None else 0

    def histogram_series(self, name: str) -> List[Tuple[Tuple, int, float]]:
        """(label key, count, sum) per series — stage-breakdown reads."""
        with self._lock:
            return [(key, entry[0], entry[1])
                    for key, entry in self._hists.get(name, {}).items()]

    # -- reads -----------------------------------------------------------

    def counter_value(self, name: str, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._counters.get(name, {}).get(key, 0.0)

    def counter_total(self, name: str) -> float:
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    def snapshot(self, identity: Optional[Dict[str, Any]] = None) -> Dict:
        """JSON-able point-in-time dump of every series, tagged with a
        process ``identity`` ({host, pid, process_index}) — the unit of
        cross-host federation (``observability/fleet.py``).  Label keys
        serialize as ``[[k, v], ...]`` pairs; histogram entries carry
        their bucket bounds so a merge can verify compatibility."""
        with self._lock:
            return {
                'identity': dict(identity or {}),
                'counters': {
                    name: [[list(map(list, key)), value]
                           for key, value in series.items()]
                    for name, series in self._counters.items()},
                'gauges': {
                    name: [[list(map(list, key)), value]
                           for key, value in series.items()]
                    for name, series in self._gauges.items()},
                'hists': {
                    name: {
                        'buckets': list(
                            self._buckets.get(name, _DEFAULT_BUCKETS)),
                        'series': [[list(map(list, key)), entry[0],
                                    entry[1], list(entry[2])]
                                   for key, entry in series.items()],
                    }
                    for name, series in self._hists.items()},
                'reset_on_close': sorted(self._reset_on_close),
            }

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: List[str] = []
        with self._lock:
            for name in sorted(self._counters):
                _append_help(out, name)
                out.append(f'# TYPE {name} counter')
                for key, value in sorted(self._counters[name].items()):
                    out.append(f'{name}{_fmt_labels(key)} {_fmt(value)}')
            for name in sorted(self._gauges):
                _append_help(out, name)
                out.append(f'# TYPE {name} gauge')
                for key, value in sorted(self._gauges[name].items()):
                    out.append(f'{name}{_fmt_labels(key)} {_fmt(value)}')
            for name in sorted(self._hists):
                _append_help(out, name)
                out.append(f'# TYPE {name} histogram')
                bounds = self._buckets.get(name, _DEFAULT_BUCKETS)
                for key, (count, total, buckets) in sorted(
                        self._hists[name].items()):
                    # observe() already stores cumulative bucket counts
                    for bound, b in zip(bounds, buckets):
                        lk = key + (('le', _fmt(bound)),)
                        out.append(
                            f'{name}_bucket{_fmt_labels(lk)} {b}')
                    lk = key + (('le', '+Inf'),)
                    out.append(f'{name}_bucket{_fmt_labels(lk)} {count}')
                    out.append(f'{name}_sum{_fmt_labels(key)} '
                               f'{_fmt(total)}')
                    out.append(f'{name}_count{_fmt_labels(key)} {count}')
        return '\n'.join(out) + '\n'


def _append_help(out: List[str], name: str) -> None:
    """# HELP line from the metric catalog (every exported name is
    cataloged — enforced by scripts/check_metric_names.py)."""
    from .catalog import METRICS
    metric = METRICS.get(name)
    if metric is not None:
        out.append(f'# HELP {name} {metric.help}')


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _fmt_labels(key: Tuple) -> str:
    if not key:
        return ''
    parts = ','.join(f'{k}="{v}"' for k, v in key)
    return '{' + parts + '}'


# -- process-global registry ------------------------------------------------
# The daemons create one registry in cmd/internal.Setup; subsystems that
# cannot take a registry parameter (device pipeline, webhook timing)
# publish through this hook.  None until configured: every emit site
# checks and no-ops, so an unconfigured process pays one attribute read.

_GLOBAL: Optional[MetricsRegistry] = None


def set_global_registry(registry: Optional[MetricsRegistry]) -> None:
    global _GLOBAL
    _GLOBAL = registry


def global_registry() -> Optional[MetricsRegistry]:
    return _GLOBAL


# instrument names (reference: pkg/metrics/metrics.go:91-224)
POLICY_RESULTS = 'kyverno_policy_results_total'
POLICY_EXECUTION_DURATION = 'kyverno_policy_execution_duration_seconds'
POLICY_CHANGES = 'kyverno_policy_changes_total'
ADMISSION_REVIEW_DURATION = 'kyverno_admission_review_duration_seconds'
ADMISSION_REQUESTS = 'kyverno_admission_requests_total'
CLIENT_QUERIES = 'kyverno_client_queries_total'


def record_policy_results(registry: MetricsRegistry, response,
                          operation: str = '') -> None:
    """reference: pkg/metrics/policyresults/metrics.go"""
    pr = response.policy_response
    for rule in pr.rules:
        registry.inc(
            POLICY_RESULTS,
            policy_name=pr.policy_name,
            rule_name=rule.name,
            rule_result=str(rule.status),
            rule_type=str(rule.rule_type),
            resource_kind=pr.resource_kind,
            resource_namespace=pr.resource_namespace,
            resource_request_operation=operation.lower())
    registry.observe(
        POLICY_EXECUTION_DURATION, pr.processing_time or 0.0,
        policy_name=pr.policy_name)
