"""Kubernetes Event generation for policy violations/applications.

Bounded workqueue drained by worker threads creating v1 Events
(reference: pkg/event/controller.go:106 Run — 3 workers, queue bound
1000 via the maxQueuedEvents flag, cmd/kyverno/main.go:234)."""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

from ..engine.api import EngineResponse, RuleStatus

SOURCE_ADMISSION = 'kyverno-admission'
SOURCE_SCAN = 'kyverno-scan'

REASON_POLICY_VIOLATION = 'PolicyViolation'
REASON_POLICY_APPLIED = 'PolicyApplied'
REASON_POLICY_ERROR = 'PolicyError'


def new_event(resource_ref: dict, reason: str, message: str,
              source: str = SOURCE_ADMISSION) -> dict:
    return {
        'apiVersion': 'v1',
        'kind': 'Event',
        'metadata': {
            'generateName': 'kyverno-event-',
            'namespace': resource_ref.get('namespace') or 'default',
        },
        'involvedObject': resource_ref,
        'reason': reason,
        'message': message,
        'source': {'component': source},
        'type': 'Warning' if reason != REASON_POLICY_APPLIED else 'Normal',
    }


def events_for_response(response: EngineResponse,
                        blocked: bool = False) -> List[dict]:
    """reference: pkg/webhooks/utils/event.go GenerateEvents"""
    pr = response.policy_response
    ref = {'kind': pr.resource_kind, 'namespace': pr.resource_namespace,
           'name': pr.resource_name, 'apiVersion': pr.resource_api_version}
    out: List[dict] = []
    for rule in pr.rules:
        if rule.status == RuleStatus.FAIL:
            out.append(new_event(
                ref, REASON_POLICY_VIOLATION,
                f'policy {pr.policy_name}/{rule.name} fail: '
                f'{rule.message}'))
        elif rule.status == RuleStatus.ERROR:
            out.append(new_event(
                ref, REASON_POLICY_ERROR,
                f'policy {pr.policy_name}/{rule.name} error: '
                f'{rule.message}'))
    return out


def _policy_ref(response: EngineResponse) -> dict:
    pr = response.policy_response
    policy = getattr(response, 'policy', None)
    kind = (policy.raw.get('kind') if policy is not None and
            getattr(policy, 'raw', None) else None) or \
        ('Policy' if pr.policy_namespace else 'ClusterPolicy')
    ref = {'apiVersion': 'kyverno.io/v1', 'kind': kind,
           'name': pr.policy_name}
    if pr.policy_namespace:
        ref['namespace'] = pr.policy_namespace
    return ref


def _resource_label(pr) -> str:
    if pr.resource_namespace:
        return (f'{pr.resource_kind} {pr.resource_namespace}/'
                f'{pr.resource_name}')
    return f'{pr.resource_kind} {pr.resource_name}'


def events_for_responses(responses: List[EngineResponse],
                         blocked: bool = False,
                         source: str = SOURCE_ADMISSION) -> List[dict]:
    """Admission-chain event generation, reference-faithful: failures
    raise PolicyViolation events on the POLICY (plus, when not blocked,
    violation events on the resource); full success raises a Normal
    PolicyApplied event on the policy (reference:
    pkg/webhooks/utils/event.go:11 GenerateEvents +
    pkg/event/events.go:12 NewPolicyFailEvent, :50
    NewPolicyAppliedEvent)."""
    out: List[dict] = []
    for er in responses:
        pr = er.policy_response
        if not pr.rules:
            continue
        statuses = [r.status for r in pr.rules]
        failed = any(s in (RuleStatus.FAIL, RuleStatus.ERROR)
                     for s in statuses)
        if failed:
            res_ref = {'kind': pr.resource_kind,
                       'namespace': pr.resource_namespace,
                       'name': pr.resource_name,
                       'apiVersion': pr.resource_api_version}
            for rule in pr.rules:
                if rule.status not in (RuleStatus.FAIL, RuleStatus.ERROR):
                    continue
                # reference: events.go:23 buildPolicyEventMessage
                msg = f'{_resource_label(pr)}: [{rule.name}] {rule.status}'
                if blocked:
                    msg += ' (blocked)'
                if rule.status == RuleStatus.ERROR and rule.message:
                    msg += f'; {rule.message}'
                ev = new_event(_policy_ref(er), REASON_POLICY_VIOLATION,
                               msg, source)
                out.append(ev)
                if not blocked:
                    out.append(new_event(
                        res_ref, REASON_POLICY_VIOLATION,
                        f'policy {pr.policy_name}/{rule.name} '
                        f'{rule.status}: {rule.message}', source))
        elif all(s == RuleStatus.SKIP for s in statuses):
            continue  # skipped: no event (exceptions handled upstream)
        else:
            out.append(new_event(
                _policy_ref(er), REASON_POLICY_APPLIED,
                f'{_resource_label(pr)}: pass', source))
    return out


class EventGenerator:
    """Buffered event emitter (reference: pkg/event/controller.go)."""

    MAX_QUEUED = 1000
    WORKERS = 3

    def __init__(self, client, max_queued: Optional[int] = None):
        self.client = client
        self._queue: 'queue.Queue[dict]' = queue.Queue(
            maxsize=max_queued or self.MAX_QUEUED)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self.dropped = 0

    def add(self, *events: dict) -> None:
        for ev in events:
            try:
                self._queue.put_nowait(ev)
            except queue.Full:
                self.dropped += 1  # the reference drops on overflow too

    def run(self) -> None:
        for _ in range(self.WORKERS):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                ev = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._emit(ev)
            self._queue.task_done()

    def _emit(self, ev: dict) -> None:
        ns = ev['metadata'].get('namespace', 'default')
        ev = dict(ev)
        ev.setdefault('metadata', {})
        ev['metadata'] = dict(ev['metadata'])
        ev['metadata']['name'] = \
            f"{ev['metadata'].get('generateName', 'ev-')}{time.time_ns()}"
        try:
            self.client.create_resource('v1', 'Event', ns, ev)
        except Exception:  # noqa: BLE001 - event loss is tolerated
            pass

    def drain(self, timeout: float = 5.0) -> None:
        deadline = time.time() + timeout
        while self._queue.unfinished_tasks and time.time() < deadline:
            time.sleep(0.01)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads = []
