"""Kubernetes Event generation for policy violations/applications.

Bounded workqueue drained by worker threads creating v1 Events
(reference: pkg/event/controller.go:106 Run — 3 workers, queue bound
1000 via the maxQueuedEvents flag, cmd/kyverno/main.go:234)."""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

from ..engine.api import EngineResponse, RuleStatus

SOURCE_ADMISSION = 'kyverno-admission'
SOURCE_SCAN = 'kyverno-scan'

REASON_POLICY_VIOLATION = 'PolicyViolation'
REASON_POLICY_APPLIED = 'PolicyApplied'
REASON_POLICY_ERROR = 'PolicyError'


def new_event(resource_ref: dict, reason: str, message: str,
              source: str = SOURCE_ADMISSION) -> dict:
    return {
        'apiVersion': 'v1',
        'kind': 'Event',
        'metadata': {
            'generateName': 'kyverno-event-',
            'namespace': resource_ref.get('namespace') or 'default',
        },
        'involvedObject': resource_ref,
        'reason': reason,
        'message': message,
        'source': {'component': source},
        'type': 'Warning' if reason != REASON_POLICY_APPLIED else 'Normal',
    }


def events_for_response(response: EngineResponse,
                        blocked: bool = False) -> List[dict]:
    """reference: pkg/webhooks/utils/event.go GenerateEvents"""
    pr = response.policy_response
    ref = {'kind': pr.resource_kind, 'namespace': pr.resource_namespace,
           'name': pr.resource_name, 'apiVersion': pr.resource_api_version}
    out: List[dict] = []
    for rule in pr.rules:
        if rule.status == RuleStatus.FAIL:
            out.append(new_event(
                ref, REASON_POLICY_VIOLATION,
                f'policy {pr.policy_name}/{rule.name} fail: '
                f'{rule.message}'))
        elif rule.status == RuleStatus.ERROR:
            out.append(new_event(
                ref, REASON_POLICY_ERROR,
                f'policy {pr.policy_name}/{rule.name} error: '
                f'{rule.message}'))
    return out


class EventGenerator:
    """Buffered event emitter (reference: pkg/event/controller.go)."""

    MAX_QUEUED = 1000
    WORKERS = 3

    def __init__(self, client, max_queued: Optional[int] = None):
        self.client = client
        self._queue: 'queue.Queue[dict]' = queue.Queue(
            maxsize=max_queued or self.MAX_QUEUED)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self.dropped = 0

    def add(self, *events: dict) -> None:
        for ev in events:
            try:
                self._queue.put_nowait(ev)
            except queue.Full:
                self.dropped += 1  # the reference drops on overflow too

    def run(self) -> None:
        for _ in range(self.WORKERS):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                ev = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._emit(ev)
            self._queue.task_done()

    def _emit(self, ev: dict) -> None:
        ns = ev['metadata'].get('namespace', 'default')
        ev = dict(ev)
        ev.setdefault('metadata', {})
        ev['metadata'] = dict(ev['metadata'])
        ev['metadata']['name'] = \
            f"{ev['metadata'].get('generateName', 'ev-')}{time.time_ns()}"
        try:
            self.client.create_resource('v1', 'Event', ns, ev)
        except Exception:  # noqa: BLE001 - event loss is tolerated
            pass

    def drain(self, timeout: float = 5.0) -> None:
        deadline = time.time() + timeout
        while self._queue.unfinished_tasks and time.time() < deadline:
            time.sleep(0.01)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads = []
