"""Fleet observatory: mesh-step skew analysis + cross-host metric
federation.

The single-host observability stack (stage spans, coverage, SLO burn,
critical-path timelines) says nothing about the sharded mesh path —
``parallel/mesh.py`` dispatches over N devices and, before this module,
emitted no per-shard attribution at all.  Three pieces close that gap:

* **Mesh-step telemetry** — ``distributed_scan_step`` feeds every
  sharded dispatch through :func:`record_step`: per-shard device-eval
  walls (host-side ``block_until_ready`` splits, in device order, with
  the ``mesh_shard`` fault site timed inside each split so injected
  straggler delays attribute to exactly one shard), per-shard row
  occupancy, collective (psum/allgather) wall and padding waste.  The
  metric writes themselves live in ``parallel/mesh.py`` (ktpu-lint
  KTPU509 requires the shard/host identity labels at those sites).

* **Straggler blame** — :class:`SkewAnalyzer` keeps a sliding window
  (``KTPU_FLEET_SKEW_WINDOW``) of per-step skew ratios (max-shard /
  mean-shard) per mesh shape.  Sustained skew with a stable slowest
  shard names the device, renders a ``bound_by=straggler`` verdict
  through the critical-path advisor (``timeline.advise``) and fires
  the rate-limited deep profile (``profiling.deep_profile``, same
  single-fire/backoff contract as the SLO engine's auto-capture).

* **Cross-host federation** — :class:`FleetRegistry` snapshots each
  process's ``MetricsRegistry`` tagged ``{host, pid, process_index}``
  and merges snapshots: counters sum, histograms merge bucket-wise,
  gauges follow residency rules (occupancy gauges marked
  ``reset_on_close`` sum across the fleet; state gauges take the max).
  Snapshots arrive by pull (``GET /debug/fleet``), by JSONL files from
  a bench run (``scripts/fleet_report.py``), or programmatically
  (:meth:`FleetRegistry.add_snapshot` — keyed by identity, so re-adding
  a host's snapshot replaces it and the merge stays idempotent).

Contract: everything here is a no-op until :func:`configure` runs, and
``KTPU_FLEET=0`` keeps it off even then — the mesh path is
bit-identical to a build without this module (pinned by
``tests/test_distributed.py``).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .metrics import MetricsRegistry

_log = logging.getLogger(__name__)

# metric names written by the mesh path (the write sites live in
# parallel/mesh.py so KTPU509 can hold them to the fleet_scope labels)
MESH_STEP_DURATION = 'kyverno_tpu_mesh_step_duration_seconds'
MESH_SHARD_SKEW = 'kyverno_tpu_mesh_shard_skew_ratio'
MESH_COLLECTIVE_SECONDS = 'kyverno_tpu_mesh_collective_seconds_total'
MESH_PADDING_ROWS = 'kyverno_tpu_mesh_padding_rows_total'

#: windowed mean skew at or above this names a sustained straggler
SKEW_SUSTAINED_RATIO = 2.0
#: seconds between straggler-triggered deep profiles (same backoff
#: contract as observability/slo.py's burn-rate auto-capture)
PROFILE_MIN_INTERVAL_S = 60.0


def _skew_window() -> int:
    try:
        return max(2, int(os.environ.get('KTPU_FLEET_SKEW_WINDOW', '16')))
    except ValueError:
        return 16


def identity() -> Dict[str, Any]:
    """This process's federation identity: {host, pid, process_index}.
    ``process_index`` is jax's distributed rank when a backend is
    initialized, else 0 — never pays backend bring-up."""
    process_index = 0
    try:
        import sys
        if 'jax' in sys.modules:
            import jax
            from jax._src import xla_bridge
            if getattr(xla_bridge, '_backends', None):
                process_index = jax.process_index()
    except Exception:  # noqa: BLE001 - identity must never fail
        process_index = 0
    return {'host': socket.gethostname(), 'pid': os.getpid(),
            'process_index': process_index}


def _identity_key(ident: Dict[str, Any]) -> Tuple:
    return (str(ident.get('host', '')), int(ident.get('pid', 0)),
            int(ident.get('process_index', 0)))


# -- straggler blame ---------------------------------------------------------


class SkewAnalyzer:
    """Sliding-window shard-skew analysis per mesh shape.

    One step's skew is ``max(shard_walls) / mean(shard_walls)`` — 1.0
    is perfectly balanced.  A window of steps with high mean skew AND a
    stable slowest shard is a *straggler*: the verdict names the shard
    and its device, carries ``bound_by=straggler`` for the critical-path
    advisor, and (once per :data:`PROFILE_MIN_INTERVAL_S`) captures a
    deep profile of the stalling process.
    """

    def __init__(self, window: Optional[int] = None,
                 now: Callable[[], float] = time.monotonic,
                 profile_trigger: Optional[Callable[[], Any]] = None):
        self.window = window or _skew_window()
        self.now = now
        self.profile_trigger = profile_trigger
        self._windows: Dict[str, deque] = {}
        self._sustained: Dict[str, bool] = {}
        self._last_profile = -PROFILE_MIN_INTERVAL_S
        self._lock = threading.Lock()
        self.auto_profiles = 0
        self.last_verdict: Optional[Dict[str, Any]] = None

    def fold(self, mesh_key: str, shard_walls: Sequence[float],
             devices: Sequence[str]) -> Dict[str, Any]:
        """Fold one step's per-shard walls in; returns the step verdict
        (skew ratio, slowest shard/device, sustained flag and — when
        sustained — the advisor's straggler note)."""
        walls = [max(0.0, float(w)) for w in shard_walls]
        mean = sum(walls) / len(walls) if walls else 0.0
        peak = max(walls) if walls else 0.0
        skew = (peak / mean) if mean > 0 else 1.0
        slow = walls.index(peak) if walls else 0
        fire = False
        with self._lock:
            win = self._windows.setdefault(
                mesh_key, deque(maxlen=self.window))
            win.append((skew, slow))
            full = len(win) >= self.window
            mean_skew = sum(s for s, _ in win) / len(win)
            slow_counts: Dict[int, int] = {}
            for _s, sh in win:
                slow_counts[sh] = slow_counts.get(sh, 0) + 1
            modal = max(slow_counts, key=lambda k: slow_counts[k])
            stable = slow_counts[modal] * 2 >= len(win)
            sustained = bool(full and stable and
                             mean_skew >= SKEW_SUSTAINED_RATIO)
            was = self._sustained.get(mesh_key, False)
            self._sustained[mesh_key] = sustained
            if sustained and not was:
                t = self.now()
                if t - self._last_profile >= PROFILE_MIN_INTERVAL_S:
                    self._last_profile = t
                    self.auto_profiles += 1
                    fire = True
        device = str(devices[slow]) if slow < len(devices) else str(slow)
        verdict: Dict[str, Any] = {
            'mesh': mesh_key,
            'skew': round(skew, 4),
            'window_mean_skew': round(mean_skew, 4),
            'slow_shard': slow,
            'device': device,
            'sustained': sustained,
        }
        if sustained:
            # the straggler verdict rides the same advisor surface the
            # pipeline critical path uses: the excess fraction is how
            # much of the slowest shard's wall is pure imbalance
            from . import timeline
            frac = 1.0 - (mean / peak) if peak > 0 else 0.0
            suggest, note = timeline.advise(
                'straggler', frac, detail=f'shard {slow} ({device})')
            verdict['bound_by'] = 'straggler'
            verdict['suggest'] = suggest
            verdict['note'] = note
        with self._lock:
            self.last_verdict = verdict
        if fire:
            self._capture(verdict)
        return verdict

    def _capture(self, verdict: Dict[str, Any]) -> None:
        trigger = self.profile_trigger
        if trigger is None:
            from . import profiling

            def trigger():
                return profiling.deep_profile(seconds=2.0,
                                              trigger='mesh_skew')
        _log.error(
            'sustained mesh skew (mean %.2fx over %d steps, straggler '
            '%s): capturing auto-profile', verdict['window_mean_skew'],
            self.window, verdict['device'])

        def work():
            try:
                trigger()
            except Exception:  # noqa: BLE001 - capture is best-effort
                _log.exception('mesh-skew auto-profile capture failed')

        threading.Thread(target=work, name='ktpu-fleet-profile',
                         daemon=True).start()

    def verdict(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self.last_verdict) if self.last_verdict else None


# -- federation --------------------------------------------------------------


def _series_map(entries: List) -> Dict[Tuple, float]:
    return {tuple(tuple(pair) for pair in key): value
            for key, value in entries}


def _series_list(series: Dict[Tuple, Any]) -> List:
    return [[list(map(list, key)), value]
            for key, value in sorted(series.items())]


class FleetRegistry:
    """Per-process metric snapshots keyed by identity + their merge.

    Merge rules (the federation's residency semantics):

    * **counters** — sum across processes (monotone totals compose);
    * **histograms** — counts, sums and bucket counts sum when bucket
      bounds agree; a bounds conflict keeps the larger-count series
      and flags ``bucket_conflict`` instead of fabricating quantiles;
    * **gauges** — occupancy gauges (``mark_reset_on_close`` residency
      set: queue depths, in-flight chunks, breaker states) sum — fleet
      occupancy is the sum of per-host occupancy; all other gauges
      take the max across processes (a ratio/state gauge averaged over
      hosts would describe no process at all).

    ``add_snapshot`` keys by ``{host, pid, process_index}``, so merging
    is idempotent (re-adding a host's snapshot replaces it) and
    associative (the merged doc of merged docs equals the flat merge).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry
        self._snapshots: Dict[Tuple, Dict] = {}
        self._lock = threading.Lock()

    def local_snapshot(self) -> Optional[Dict]:
        if self._registry is None:
            return None
        return self._registry.snapshot(identity())

    def add_snapshot(self, doc: Dict) -> None:
        """Fold one process's snapshot in (identity-keyed upsert)."""
        key = _identity_key(doc.get('identity') or {})
        with self._lock:
            self._snapshots[key] = doc

    def snapshots(self) -> List[Dict]:
        """Every known snapshot, the local registry's freshest first."""
        with self._lock:
            remote = [doc for _k, doc in sorted(self._snapshots.items())]
        local = self.local_snapshot()
        if local is not None:
            lkey = _identity_key(local['identity'])
            remote = [d for d in remote
                      if _identity_key(d.get('identity') or {}) != lkey]
            return [local] + remote
        return remote

    @staticmethod
    def merge(docs: Sequence[Dict]) -> Dict:
        """Merge snapshot docs (or previously merged docs) into one."""
        counters: Dict[str, Dict[Tuple, float]] = {}
        gauges: Dict[str, Dict[Tuple, float]] = {}
        gauge_rule: Dict[str, str] = {}
        hists: Dict[str, Dict] = {}
        identities: List[Dict] = []
        seen = set()
        for doc in docs:
            for ident in (doc.get('identities') or
                          [doc.get('identity') or {}]):
                key = _identity_key(ident)
                if key not in seen:
                    seen.add(key)
                    identities.append(dict(ident))
            residency = set(doc.get('reset_on_close') or [])
            for name, entries in (doc.get('counters') or {}).items():
                dst = counters.setdefault(name, {})
                for key, value in _series_map(entries).items():
                    dst[key] = dst.get(key, 0.0) + value
            for name, entries in (doc.get('gauges') or {}).items():
                rule = 'sum' if name in residency else \
                    gauge_rule.get(name, 'max')
                gauge_rule[name] = rule
                dst = gauges.setdefault(name, {})
                for key, value in _series_map(entries).items():
                    if rule == 'sum':
                        dst[key] = dst.get(key, 0.0) + value
                    else:
                        dst[key] = max(dst.get(key, value), value)
            for name, h in (doc.get('hists') or {}).items():
                bounds = list(h.get('buckets') or [])
                dst_h = hists.setdefault(
                    name, {'buckets': bounds, 'series': {},
                           'bucket_conflict': False})
                compatible = dst_h['buckets'] == bounds
                if not compatible:
                    dst_h['bucket_conflict'] = True
                for entry in h.get('series') or []:
                    key = tuple(tuple(pair) for pair in entry[0])
                    count, total = int(entry[1]), float(entry[2])
                    buckets = list(entry[3])
                    cur = dst_h['series'].get(key)
                    if cur is None:
                        dst_h['series'][key] = [count, total, buckets]
                    else:
                        cur[0] += count
                        cur[1] += total
                        if compatible and len(cur[2]) == len(buckets):
                            cur[2] = [a + b for a, b
                                      in zip(cur[2], buckets)]
                        elif count > cur[0] - count:
                            cur[2] = buckets
        out_resid = sorted(n for n, r in gauge_rule.items()
                           if r == 'sum')
        return {
            'identities': identities,
            'counters': {n: _series_list(s)
                         for n, s in sorted(counters.items())},
            'gauges': {n: _series_list(s)
                       for n, s in sorted(gauges.items())},
            'hists': {n: {'buckets': h['buckets'],
                          'bucket_conflict': h['bucket_conflict'],
                          # snapshot wire format ([key, count, sum,
                          # buckets]) so merged docs re-merge
                          'series': [[list(map(list, key)), v[0], v[1],
                                      list(v[2])]
                                     for key, v
                                     in sorted(h['series'].items())]}
                      for n, h in sorted(hists.items())},
            'reset_on_close': out_resid,
        }

    def merged(self) -> Dict:
        return self.merge(self.snapshots())

    @staticmethod
    def counter_totals(doc: Dict) -> Dict[str, float]:
        """name → summed value across every series of ``doc`` (a
        snapshot or a merged doc) — the lossless-round-trip check."""
        out: Dict[str, float] = {}
        for name, entries in (doc.get('counters') or {}).items():
            out[name] = sum(value for _key, value in entries)
        return out

    def report(self) -> Dict[str, Any]:
        """The ``GET /debug/fleet`` body."""
        snaps = self.snapshots()
        analyzer = _analyzer
        return {
            'enabled': True,
            'identity': identity(),
            'processes': [s.get('identity') or {} for s in snaps],
            'merged': self.merge(snaps),
            'skew': analyzer.verdict() if analyzer is not None else None,
        }

    def render_table(self) -> str:
        """Terminal view (``?format=table``): merged counters/gauges
        one row each, plus the process census and skew verdict."""
        report = self.report()
        merged = report['merged']
        lines = ['fleet: %d process(es)' % len(report['processes'])]
        for ident in report['processes']:
            lines.append('  %s pid=%s process_index=%s' % (
                ident.get('host', '?'), ident.get('pid', '?'),
                ident.get('process_index', '?')))
        skew = report.get('skew')
        if skew:
            lines.append('skew: %(mesh)s %(skew).2fx slow_shard='
                         '%(slow_shard)d sustained=%(sustained)s'
                         % {**skew, 'skew': float(skew['skew'])})
        lines.append('')
        lines.append('%-52s %14s' % ('merged counter', 'total'))
        for name, entries in merged['counters'].items():
            total = sum(v for _k, v in entries)
            lines.append('%-52s %14g' % (name, total))
        lines.append('%-52s %14s' % ('merged gauge', 'value'))
        for name, entries in merged['gauges'].items():
            total = sum(v for _k, v in entries)
            lines.append('%-52s %14g' % (name, total))
        return '\n'.join(lines) + '\n'


# -- snapshot files (offline bench merge) ------------------------------------


def write_snapshot(path: str,
                   registry: Optional[MetricsRegistry] = None) -> Dict:
    """Append this process's snapshot as one JSONL line (the per-host
    artifact a bench run leaves behind for offline federation)."""
    reg = registry or (_fleet._registry if _fleet is not None else None)
    if reg is None:
        raise RuntimeError('fleet snapshot needs a configured registry')
    doc = reg.snapshot(identity())
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, 'a') as f:
        f.write(json.dumps(doc, sort_keys=True) + '\n')
    return doc


def read_snapshot_files(paths: Sequence[str]) -> List[Dict]:
    """Parse per-host JSONL snapshot files into snapshot docs."""
    docs: List[Dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    docs.append(json.loads(line))
    return docs


# -- mesh-step hook (called from parallel/mesh.py) ---------------------------


def record_step(mesh_key: str, shard_walls: Sequence[float],
                devices: Sequence[str]) -> Dict[str, Any]:
    """Feed one mesh step's per-shard walls to the skew analyzer;
    returns the verdict for the caller's span attrs / gauge write."""
    analyzer = _analyzer
    if analyzer is None:
        return {'skew': 1.0, 'slow_shard': 0, 'sustained': False,
                'mesh': mesh_key, 'device': ''}
    return analyzer.fold(mesh_key, shard_walls, devices)


# -- module state ------------------------------------------------------------


_fleet: Optional[FleetRegistry] = None
_analyzer: Optional[SkewAnalyzer] = None


def configure(registry: Optional[MetricsRegistry] = None,
              window: Optional[int] = None,
              now: Callable[[], float] = time.monotonic,
              profile_trigger: Optional[Callable[[], Any]] = None
              ) -> Optional[FleetRegistry]:
    """Arm the fleet observatory.  ``KTPU_FLEET=0`` keeps it off (the
    mesh path stays bit-identical to a build without this module);
    returns the installed :class:`FleetRegistry` or None."""
    global _fleet, _analyzer
    if os.environ.get('KTPU_FLEET', '1') == '0':
        _fleet = None
        _analyzer = None
        return None
    _fleet = FleetRegistry(registry)
    _analyzer = SkewAnalyzer(window=window, now=now,
                             profile_trigger=profile_trigger)
    return _fleet


def disable() -> None:
    global _fleet, _analyzer
    _fleet = None
    _analyzer = None


def enabled() -> bool:
    """Hot-path gate: one module-global read (devtel contract)."""
    return _fleet is not None


def fleet() -> Optional[FleetRegistry]:
    return _fleet


def analyzer() -> Optional[SkewAnalyzer]:
    return _analyzer


def registry() -> Optional[MetricsRegistry]:
    return _fleet._registry if _fleet is not None else None
