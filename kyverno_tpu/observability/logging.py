"""Structured logging setup: text or JSON formats over stdlib logging
(reference: pkg/logging/log.go — logr over zap/klog, the
``loggingFormat`` flag in cmd/internal/flag.go:35)."""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Dict

FORMAT_TEXT = 'text'
FORMAT_JSON = 'json'


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            'ts': time.strftime('%Y-%m-%dT%H:%M:%SZ',
                                time.gmtime(record.created)),
            'level': record.levelname.lower(),
            'logger': record.name,
            'msg': record.getMessage(),
        }
        extra = getattr(record, 'kv', None)
        if extra:
            out.update(extra)
        if record.exc_info:
            out['error'] = self.formatException(record.exc_info)
        return json.dumps(out)


def setup(fmt: str = FORMAT_TEXT, level: int = logging.INFO
          ) -> logging.Logger:
    root = logging.getLogger('kyverno')
    root.setLevel(level)
    root.handlers = []
    handler = logging.StreamHandler(sys.stderr)
    if fmt == FORMAT_JSON:
        handler.setFormatter(JSONFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            '%(asctime)s %(levelname)s %(name)s %(message)s'))
    root.addHandler(handler)
    return root


def with_values(logger: logging.Logger, msg: str, level: int = logging.INFO,
                **kv) -> None:
    """logr-style key/value logging."""
    logger.log(level, msg, extra={'kv': kv})
