"""Serving SLO engine: sliding-window burn rate on admission latency.

The admission path has latency histograms but no *objective*: nothing
in the process knows whether p99 is inside budget, so regressions are
found by reading dashboards after the fact.  This module attaches the
objective (`KTPU_SLO_P99_MS` at quantile `KTPU_SLO_TARGET`) and
computes **multi-window burn rate** over a sliding time window
(`KTPU_SLO_WINDOW_S`), the SRE alerting construct: with error budget
``1 - target``, ``burn = error_rate / (1 - target)`` — burn 1.0 spends
exactly the budget over the window, burn N spends it N× too fast.  The
degraded verdict requires BOTH the long window (the full
``KTPU_SLO_WINDOW_S``) and a short window (one ring slice,
``window / 12``) to burn past :data:`BURN_DEGRADED`, so a single slow
decision cannot flap the verdict and a recovered server clears it
within one slice.

Implementation: a fixed-bucket latency digest sliced over a time ring —
``SLICES`` slices each covering ``window / SLICES`` seconds, per
serving path (``batch | sync | shed | host_fallback``).  ``record``
lands a decision in the current slice (O(buckets)); reads sum the
slices still inside the window.  No dependencies, bounded memory
(slices × paths × buckets counters).

Exports: ``kyverno_tpu_slo_burn_rate{window=short|long}`` and
``kyverno_tpu_slo_budget_remaining`` gauges, ``GET /debug/slo``, and
the verdict folded into the webhook ``GET /health`` payload.  When the
degraded transition fires, an **auto-profile** captures a deep profile
once, rate-limited (:data:`PROFILE_MIN_INTERVAL_S`), through
``observability.profiling.deep_profile`` — the same auto-capture
pattern as the d2h stall watchdog's flight-recorder dump, giving every
burn alert a flamegraph of what the server was doing as it crossed.

Off by default: ``KTPU_SLO_WINDOW_S=0`` (the shipped default) makes
every hook a no-op and the admission path bit-identical, pinned by
``tests/test_slo.py``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .metrics import MetricsRegistry, global_registry

SLO_BURN_RATE = 'kyverno_tpu_slo_burn_rate'
SLO_BUDGET_REMAINING = 'kyverno_tpu_slo_budget_remaining'

#: latency bucket bounds, milliseconds — spans sub-ms cache replays to
#: the host-loop sweeps of 1k-policy sets
BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
              1000.0, 2500.0, 5000.0, 10000.0)

#: time slices per window: reads sum full slices, so resolution is
#: window/12 and the short burn window is exactly one slice
SLICES = 12

#: burn rate at which the verdict degrades (both windows must cross);
#: 1.0 = spending the error budget exactly at the sustainable rate
BURN_DEGRADED = 1.0

#: floor between auto-profile captures (per process)
PROFILE_MIN_INTERVAL_S = 60.0

_DEFAULT_P99_MS = 500.0
_DEFAULT_TARGET = 0.99

_log = logging.getLogger('kyverno.slo')


def _to_float(raw: Optional[str], default: float) -> float:
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class SloEngine:
    """Sliding-window latency digests + burn-rate computation.

    ``now`` is injectable (tests drive synthetic clocks); defaults to
    ``time.monotonic`` — wall-clock jumps must not spill slices."""

    def __init__(self, window_s: float, p99_ms: float, target: float,
                 registry: Optional[MetricsRegistry] = None,
                 now: Callable[[], float] = time.monotonic,
                 profile_trigger: Optional[Callable[[], Any]] = None):
        self.window_s = window_s
        self.objective_ms = p99_ms
        self.target = min(max(target, 0.0), 0.9999)
        self.registry = registry
        self.now = now
        self.profile_trigger = profile_trigger
        self.slice_s = window_s / SLICES
        self._lock = threading.Lock()
        # ring: SLICES entries of {path: [count, over, bucket_counts]},
        # each stamped with the absolute slice epoch it covers so stale
        # slices are recognized lazily instead of swept by a thread
        self._slices: List[Dict[str, List[Any]]] = \
            [{} for _ in range(SLICES)]
        self._epochs: List[int] = [-1] * SLICES
        self._degraded = False
        self._last_profile = float('-inf')
        self.auto_profiles = 0

    # -- writes ------------------------------------------------------------

    def record(self, path: str, duration_s: float) -> None:
        ms = duration_s * 1000.0
        epoch = int(self.now() / self.slice_s)
        idx = epoch % SLICES
        with self._lock:
            if self._epochs[idx] != epoch:
                self._slices[idx] = {}
                self._epochs[idx] = epoch
            entry = self._slices[idx].get(path)
            if entry is None:
                entry = [0, 0, [0] * (len(BUCKETS_MS) + 1)]
                self._slices[idx][path] = entry
            entry[0] += 1
            if ms > self.objective_ms:
                entry[1] += 1
            for i, bound in enumerate(BUCKETS_MS):
                if ms <= bound:
                    entry[2][i] += 1
                    break
            else:
                entry[2][len(BUCKETS_MS)] += 1
            burn_short, burn_long, remaining = self._burn_locked(epoch)
            degraded = burn_short >= BURN_DEGRADED and \
                burn_long >= BURN_DEGRADED
            crossed = degraded and not self._degraded
            self._degraded = degraded
        self._publish(burn_short, burn_long, remaining)
        if crossed:
            self._auto_profile(burn_short, burn_long)

    # -- burn math ---------------------------------------------------------

    def _window_totals(self, epoch: int, n_slices: int,
                       by_path: Optional[Dict[str, List[Any]]] = None
                       ) -> tuple:
        """(count, over) across the ``n_slices`` most recent slices
        (inclusive of the current one).  Called under the lock."""
        count = over = 0
        for back in range(n_slices):
            want = epoch - back
            if want < 0:
                break
            idx = want % SLICES
            if self._epochs[idx] != want:
                continue  # stale or never-filled slice
            for path, entry in self._slices[idx].items():
                count += entry[0]
                over += entry[1]
                if by_path is not None:
                    agg = by_path.setdefault(
                        path, [0, 0, [0] * (len(BUCKETS_MS) + 1)])
                    agg[0] += entry[0]
                    agg[1] += entry[1]
                    for i, b in enumerate(entry[2]):
                        agg[2][i] += b
        return count, over

    def _burn_locked(self, epoch: int) -> tuple:
        """(burn_short, burn_long, budget_remaining); under the lock."""
        budget = 1.0 - self.target
        l_count, l_over = self._window_totals(epoch, SLICES)
        s_count, s_over = self._window_totals(epoch, 1)
        burn_long = (l_over / l_count) / budget if l_count else 0.0
        burn_short = (s_over / s_count) / budget if s_count else 0.0
        remaining = 1.0 - burn_long
        return burn_short, burn_long, remaining

    def _publish(self, burn_short: float, burn_long: float,
                 remaining: float) -> None:
        reg = self.registry or global_registry()
        if reg is None:
            return
        # burn rate is a live condition of THIS process — a drained
        # server must export 0, not its last degraded sample
        reg.mark_reset_on_close(SLO_BURN_RATE)
        reg.mark_reset_on_close(SLO_BUDGET_REMAINING)
        reg.set_gauge(SLO_BURN_RATE, round(burn_short, 6), window='short')
        reg.set_gauge(SLO_BURN_RATE, round(burn_long, 6), window='long')
        reg.set_gauge(SLO_BUDGET_REMAINING, round(remaining, 6))

    # -- auto-profile ------------------------------------------------------

    def _auto_profile(self, burn_short: float, burn_long: float) -> None:
        """Degraded transition: capture one deep profile (py sampler +
        jax trace when a backend is live), rate-limited so a flapping
        burn cannot stack captures.  Runs on a daemon thread — the
        observing request never waits on the capture."""
        now = self.now()
        with self._lock:
            if now - self._last_profile < PROFILE_MIN_INTERVAL_S:
                return
            self._last_profile = now
            self.auto_profiles += 1
        trigger = self.profile_trigger
        if trigger is None:
            from . import profiling

            def trigger():
                return profiling.deep_profile(seconds=2.0,
                                              trigger='slo_burn')
        _log.error(
            'SLO burn-rate degraded (short=%.2f long=%.2f, objective '
            'p%g<=%.0fms over %.0fs): capturing auto-profile',
            burn_short, burn_long, self.target * 100,
            self.objective_ms, self.window_s)

        def work():
            try:
                trigger()
            except Exception:  # noqa: BLE001 - capture is best-effort
                _log.exception('slo auto-profile capture failed')

        threading.Thread(target=work, name='ktpu-slo-profile',
                         daemon=True).start()

    # -- reads -------------------------------------------------------------

    def verdict(self) -> Dict[str, Any]:
        """The compact health view folded into ``GET /health``."""
        with self._lock:
            epoch = int(self.now() / self.slice_s)
            burn_short, burn_long, remaining = self._burn_locked(epoch)
            degraded = self._degraded
        return {
            'degraded': degraded,
            'burn_rate_short': round(burn_short, 4),
            'burn_rate_long': round(burn_long, 4),
            'budget_remaining': round(remaining, 4),
            'objective_ms': self.objective_ms,
            'target': self.target,
            'window_s': self.window_s,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/slo`` body: the verdict plus per-path digests
        (count, over-objective count, estimated p50/p99 from the
        fixed buckets) over the long window."""
        by_path: Dict[str, List[Any]] = {}
        with self._lock:
            epoch = int(self.now() / self.slice_s)
            self._window_totals(epoch, SLICES, by_path=by_path)
        paths = {}
        for path, (count, over, buckets) in sorted(by_path.items()):
            paths[path] = {
                'count': count,
                'over_objective': over,
                'p50_ms': _bucket_quantile(buckets, 0.50),
                'p99_ms': _bucket_quantile(buckets, 0.99),
            }
        out = self.verdict()
        out['auto_profiles'] = self.auto_profiles
        out['paths'] = paths
        return out


def _bucket_quantile(buckets: List[int], q: float) -> float:
    """Upper-bound estimate of the ``q`` quantile from fixed-bucket
    counts (the bound of the bucket the quantile falls in; the overflow
    bucket reports the largest finite bound)."""
    total = sum(buckets)
    if total == 0:
        return 0.0
    rank = q * total
    seen = 0
    for i, n in enumerate(buckets):
        seen += n
        if seen >= rank and n:
            return BUCKETS_MS[i] if i < len(BUCKETS_MS) \
                else BUCKETS_MS[-1]
    return BUCKETS_MS[-1]


# -- module state -----------------------------------------------------------

_engine: Optional[SloEngine] = None


def configure(registry: Optional[MetricsRegistry] = None,
              window_s: Optional[float] = None,
              p99_ms: Optional[float] = None,
              target: Optional[float] = None,
              now: Callable[[], float] = time.monotonic,
              profile_trigger: Optional[Callable[[], Any]] = None
              ) -> Optional[SloEngine]:
    """Enable the SLO engine.  ``window_s`` defaults to
    ``KTPU_SLO_WINDOW_S`` (0, the shipped default, disables entirely —
    the off state the bit-identity tests pin against); the objective
    defaults to ``KTPU_SLO_P99_MS`` at quantile ``KTPU_SLO_TARGET``.
    Idempotent; :func:`disable` undoes it."""
    global _engine
    if window_s is None:
        window_s = _to_float(os.environ.get('KTPU_SLO_WINDOW_S'), 0.0)
    if window_s <= 0:
        disable()
        return None
    if p99_ms is None:
        p99_ms = _to_float(os.environ.get('KTPU_SLO_P99_MS'),
                           _DEFAULT_P99_MS)
    if target is None:
        target = _to_float(os.environ.get('KTPU_SLO_TARGET'),
                           _DEFAULT_TARGET)
    _engine = SloEngine(
        window_s=window_s, p99_ms=p99_ms, target=target,
        registry=registry or global_registry(), now=now,
        profile_trigger=profile_trigger)
    return _engine


def disable() -> None:
    global _engine
    _engine = None


def engine() -> Optional[SloEngine]:
    return _engine


def enabled() -> bool:
    """The zero-overhead gate the admission path checks (one global
    read)."""
    return _engine is not None


def record(path: str, duration_s: float) -> None:
    """Feed one admission decision (no-op when unconfigured).
    ``shed:<reason>`` paths fold to ``shed`` — the SLO tracks the
    serving lane, the shed taxonomy lives on
    ``kyverno_tpu_admission_shed_total``."""
    eng = _engine
    if eng is not None:
        eng.record(path.split(':', 1)[0], duration_s)


def verdict() -> Optional[Dict[str, Any]]:
    """Health-payload verdict, or None when unconfigured."""
    eng = _engine
    return eng.verdict() if eng is not None else None


def snapshot() -> Dict[str, Any]:
    """Bench / endpoint view (empty when unconfigured)."""
    eng = _engine
    return eng.snapshot() if eng is not None else {}
