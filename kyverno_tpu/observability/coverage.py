"""Device-coverage ledger: attributed host-fallback telemetry.

The engine's premise is that policy evaluation compiles to batched
device kernels, yet three independent mechanisms silently shed work to
the host interpreter: compile-time rejection (``CompileError`` →
``CompiledPolicySet.host_rules``), per-resource ``STATUS_HOST`` device
verdicts replayed by the scanner, and the mutate fast-path ``FALLBACK``
sentinel (``compiler/mutate_compile.py``).  This module makes every one
of those falls *attributed*, never silent:

* a **stable fallback-reason taxonomy** (:data:`REASONS`) — the only
  legal values of the ``reason`` label;
* per-(policy, rule) **placement records** (device | host | partial,
  with reason) exported as the ``kyverno_tpu_rule_placement_info``
  gauge and queryable as JSON (``GET /debug/coverage`` on the profile
  server, ``scripts/coverage_report.py``);
* runtime counters ``kyverno_tpu_host_fallback_total{path, reason}``
  and a per-scan ``kyverno_tpu_device_coverage_ratio`` gauge, plus the
  ``coverage`` block ``bench.py`` embeds in its JSON line.

Everything is a no-op until :func:`configure` runs (the established
``observability/device.py`` contract): an unconfigured process records
nothing, creates no series, and starts no threads, and scan output is
bit-identical either way (the ledger only observes).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace as _dc_replace
from typing import Any, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry, global_registry

RULE_PLACEMENT_INFO = 'kyverno_tpu_rule_placement_info'
HOST_FALLBACK_TOTAL = 'kyverno_tpu_host_fallback_total'
DEVICE_COVERAGE_RATIO = 'kyverno_tpu_device_coverage_ratio'

#: per-rule placement values
PLACEMENT_DEVICE = 'device'
PLACEMENT_HOST = 'host'
PLACEMENT_PARTIAL = 'partial'

#: counter ``path`` label values (mutate covers the bulk-apply fast
#: path; generate rules appear in placement records only; serving
#: covers admission-batching fallbacks decided before any scan runs)
PATHS = ('validate', 'mutate', 'pss', 'serving')

# -- fallback-reason taxonomy ------------------------------------------------
# Compile time (whole-rule placement):
REASON_UNSUPPORTED_OPERATOR = 'unsupported_operator'  # outside the device
#   vocabulary (operator / pattern shape / operand type / depth)
REASON_HOST_CLOSURE = 'host_closure'      # inherently host-bound rule
#   (verifyImages, manifests signatures — network / crypto closures)
REASON_API_CALL = 'api_call'              # context entry needs a live
#   API transport (imageRegistry)
REASON_POLICY_COUPLING = 'policy_coupling'  # rule compiled, but a
#   sibling host rule or applyRules=One couples the whole policy to host
# Runtime (per-resource cells):
REASON_STATUS_HOST = 'status_host'        # device verdict undecidable
REASON_UNSYNTHESIZABLE = 'unsynthesizable_message'  # verdict known but
#   the host's exact message cannot be synthesized from templates
REASON_CONTEXT_LOAD = 'context_load_failed'  # rule context load failed;
#   host materialization produces the exact error response
# Runtime (mutate fast-path escapes):
REASON_NON_DICT = 'non_dict_intermediate'  # overlay path hit a non-map
REASON_DUP_ELEMENT_NAMES = 'duplicate_element_names'  # merge-by-name
#   list carries duplicate / non-string names
REASON_REPLACE_PATH_MISSING = 'replace_path_missing'  # json6902 replace
#   on a path the document does not have
REASON_PRECONDITION_ESCAPE = 'precondition_escape'  # per-element
#   precondition left the compiled vocabulary at runtime
# Device-side mutate (kyverno_tpu/mutate/):
REASON_SITE_CONFLICT = 'edit_site_conflict'  # two lowered mutate rules
#   write overlapping slot paths — cumulative ordering leaves the
#   original-document device vocabulary (compile time)
REASON_PATCH_UNDECIDABLE = 'patch_undecidable'  # the encoded lanes
#   cannot decide whether the live value equals the patch constant
#   (numeric outside the exact milli window) — host applies instead
# Per-row admission lanes (compiler/admission.py):
REASON_ADMISSION_UNENCODABLE = 'admission_unencodable'  # a request's
#   admission tuple did not intern exactly into the per-row lanes
#   (non-string values, lane-width overflow) — that ROW's admission
#   match runs on the host matcher; path="serving" counts batcher
#   tickets keyed on the whole canonical tuple because their scanner
#   cannot consume per-row admissions
# Degradation under failure (serving/batcher.py quarantine,
# serving/breaker.py lifecycle, compiler/pipeline.py retries):
REASON_POISON_ROW = 'poison_row'  # quarantine bisection isolated this
#   row as the one poisoning its shared dispatch — the host loop
#   serves it while its healthy batch riders stayed on device
REASON_BREAKER_OPEN = 'breaker_open'  # the policy set's circuit
#   breaker is open (or half-open with the probe slot taken): the
#   request host-serves without touching the device path
REASON_STAGE_RETRY_EXHAUSTED = 'stage_retry_exhausted'  # a scan
#   pipeline stage kept failing after its whole KTPU_STAGE_RETRIES
#   budget; the chunk's error surfaced to the consumer

REASONS = frozenset({
    REASON_UNSUPPORTED_OPERATOR, REASON_HOST_CLOSURE, REASON_API_CALL,
    REASON_POLICY_COUPLING, REASON_STATUS_HOST, REASON_UNSYNTHESIZABLE,
    REASON_CONTEXT_LOAD, REASON_NON_DICT, REASON_DUP_ELEMENT_NAMES,
    REASON_REPLACE_PATH_MISSING, REASON_PRECONDITION_ESCAPE,
    REASON_SITE_CONFLICT, REASON_PATCH_UNDECIDABLE,
    REASON_ADMISSION_UNENCODABLE, REASON_POISON_ROW,
    REASON_BREAKER_OPEN, REASON_STAGE_RETRY_EXHAUSTED,
})


@dataclass(frozen=True)
class RulePlacement:
    """Compile-time placement of one (policy, rule) pair."""
    policy: str
    rule: str
    path: str = 'validate'        # validate | pss | mutate | generate
    placement: str = PLACEMENT_DEVICE
    reason: Optional[str] = None  # taxonomy slug for host placements
    detail: str = ''              # free-text compile diagnostic
    policy_index: int = -1


def compile_placements(policies: List[Any], cps: Any) -> List[RulePlacement]:
    """Final per-rule placement for a compiled policy set.

    Applies the scanner's policy-coupling override to the raw
    ``cps.placements``: a policy with ANY host rule — or
    ``applyRules=One`` (early-exit coupling between rules) — runs
    entirely on the host engine, so its device-compiled rules become
    ``host`` with reason ``policy_coupling``.  Shared by
    ``BatchScanner`` and ``scripts/coverage_report.py`` so the live
    ledger and the CLI can never disagree on placement.
    """
    host_idx = {p.policy_index for p in cps.placements
                if p.placement == PLACEMENT_HOST}
    host_idx |= {i for i, p in enumerate(policies)
                 if (getattr(p, 'apply_rules', None) or 'All') == 'One'}
    out: List[RulePlacement] = []
    for p in cps.placements:
        if p.placement == PLACEMENT_DEVICE and p.policy_index in host_idx:
            p = _dc_replace(
                p, placement=PLACEMENT_HOST,
                reason=REASON_POLICY_COUPLING,
                detail='rule compiled but a sibling host rule or '
                       'applyRules=One couples the policy to the host '
                       'engine')
        out.append(p)
    return out


class ScanTally:
    """Per-scan accumulator: plain dict increments on the assembly hot
    path (no locks, no metric emission per cell), absorbed into the
    global ledger in one batch when the scan finishes."""

    __slots__ = ('_ledger', 'total_rows', 'device_rows', 'host_rows',
                 'by_reason', 'rule_device', 'rule_host', '_finished')

    def __init__(self, ledger: 'CoverageLedger'):
        self._ledger = ledger
        self.total_rows = 0
        self.device_rows = 0
        self.host_rows = 0
        # (path, reason) -> rows
        self.by_reason: Dict[Tuple[str, str], int] = {}
        # (policy, rule, path) -> rows
        self.rule_device: Dict[Tuple[str, str, str], int] = {}
        # (policy, rule, path, reason) -> rows
        self.rule_host: Dict[Tuple[str, str, str, str], int] = {}
        self._finished = False

    @staticmethod
    def _path(prog) -> str:
        # device-mutate programs carry an explicit .path ('mutate');
        # validate RulePrograms are distinguished by their PSS payload
        explicit = getattr(prog, 'path', None)
        if explicit:
            return explicit
        return 'pss' if prog.pss is not None else 'validate'

    def device(self, prog) -> None:
        """One device-synthesized (resource, rule) cell."""
        self.device_rows += 1
        key = (prog.policy_name, prog.rule_name, self._path(prog))
        self.rule_device[key] = self.rule_device.get(key, 0) + 1

    def device_n(self, prog, n: int) -> None:
        """``n`` device-synthesized cells of one program at once — the
        columnar report assembly accounts whole status groups per
        vectorized column sweep instead of per cell."""
        self.device_rows += n
        key = (prog.policy_name, prog.rule_name, self._path(prog))
        self.rule_device[key] = self.rule_device.get(key, 0) + n

    def fallback(self, prog, reason: str) -> None:
        """One host-replayed cell of a device-compiled program."""
        self._host(prog.policy_name, prog.rule_name, self._path(prog),
                   reason)

    def fallback_n(self, prog, reason: str, n: int) -> None:
        """``n`` host-replayed cells of one program at once."""
        if reason not in REASONS:
            reason = 'unknown'
        self.host_rows += n
        path = self._path(prog)
        rkey = (path, reason)
        self.by_reason[rkey] = self.by_reason.get(rkey, 0) + n
        hkey = (prog.policy_name, prog.rule_name, path, reason)
        self.rule_host[hkey] = self.rule_host.get(hkey, 0) + n

    def host_rule(self, policy: str, rule: str, reason: str,
                  path: str = 'validate') -> None:
        """One rule response served by a whole-policy host run."""
        self.total_rows += 1
        self._host(policy, rule, path, reason)

    def _host(self, policy: str, rule: str, path: str, reason: str) -> None:
        if reason not in REASONS:
            reason = 'unknown'
        self.host_rows += 1
        rkey = (path, reason)
        self.by_reason[rkey] = self.by_reason.get(rkey, 0) + 1
        hkey = (policy, rule, path, reason)
        self.rule_host[hkey] = self.rule_host.get(hkey, 0) + 1

    def ratio(self) -> Optional[float]:
        if not self.total_rows:
            return None
        return self.device_rows / self.total_rows

    def finish(self) -> None:
        """Flush into the ledger (idempotent; sets the per-scan ratio
        gauge)."""
        if self._finished:
            return
        self._finished = True
        self._ledger.absorb(self)


class CoverageLedger:
    """Process-global coverage state: placement records + runtime
    fallback aggregation, rendered as metrics and as the
    ``/debug/coverage`` JSON document."""

    def __init__(self, registry: MetricsRegistry):
        self._registry = registry
        self._lock = threading.Lock()
        # (policy, rule, path) -> mutable record dict
        self._rules: Dict[Tuple[str, str, str], dict] = {}
        self._fallbacks: Dict[Tuple[str, str], int] = {}
        self.device_rows = 0
        self.host_rows = 0
        self.total_rows = 0
        self.scans = 0
        self.last_ratio: Optional[float] = None

    # -- placement ---------------------------------------------------------

    def record_placements(self, placements: List[RulePlacement]) -> None:
        with self._lock:
            for p in placements:
                self._upsert(p.policy, p.rule, p.path, p.placement,
                             p.reason, p.detail)

    def _upsert(self, policy: str, rule: str, path: str, placement: str,
                reason: Optional[str], detail: str = '') -> dict:
        key = (policy, rule, path)
        rec = self._rules.get(key)
        if rec is None:
            rec = {'policy': policy, 'rule': rule, 'path': path,
                   'placement': placement, 'reason': reason,
                   'detail': detail, 'device_rows': 0, 'host_rows': 0,
                   'emitted': None}
            self._rules[key] = rec
        else:
            rec['placement'] = placement
            rec['reason'] = reason
            if detail:
                rec['detail'] = detail
        self._emit_placement(rec)
        return rec

    @staticmethod
    def _effective(rec: dict) -> str:
        """Live placement: a device rule with observed host rows is
        ``partial`` (compile-time ``placement`` stays untouched in the
        JSON report so the CLI's compile-only view always agrees)."""
        if rec['placement'] == PLACEMENT_DEVICE and rec['host_rows']:
            return PLACEMENT_PARTIAL
        return rec['placement']

    def _emit_placement(self, rec: dict) -> None:
        labels = {'policy': rec['policy'], 'rule': rec['rule'],
                  'path': rec['path'], 'placement': self._effective(rec),
                  'reason': rec['reason'] or ''}
        emitted = rec['emitted']
        if emitted == labels:
            return
        if emitted is not None:
            self._registry.clear_gauge(RULE_PLACEMENT_INFO, **emitted)
        self._registry.set_gauge(RULE_PLACEMENT_INFO, 1.0, **labels)
        rec['emitted'] = labels

    # -- runtime -----------------------------------------------------------

    def record_fallback(self, path: str, reason: str, policy: str = '',
                        rule: str = '', rows: int = 1) -> None:
        """One attributed host fallback outside a scan tally (mutate
        fast-path escapes, mesh summaries)."""
        if reason not in REASONS:
            reason = 'unknown'
        with self._lock:
            self._registry.inc(HOST_FALLBACK_TOTAL, float(rows),
                               path=path, reason=reason)
            key = (path, reason)
            self._fallbacks[key] = self._fallbacks.get(key, 0) + rows
            self.host_rows += rows
            self.total_rows += rows
            if policy or rule:
                rec = self._rules.get((policy, rule, path))
                if rec is None:
                    rec = self._upsert(policy, rule, path,
                                       PLACEMENT_DEVICE, None)
                rec['host_rows'] += rows
                self._emit_placement(rec)

    def record_scan(self, device_rows: int, host_rows: int,
                    path: str = 'validate',
                    reason: str = REASON_STATUS_HOST) -> None:
        """One whole-scan outcome where per-cell attribution is a single
        reason (the mesh summary path: host rows are STATUS_HOST counts
        from the verdict histogram)."""
        with self._lock:
            if host_rows:
                self._registry.inc(HOST_FALLBACK_TOTAL, float(host_rows),
                                   path=path, reason=reason)
                key = (path, reason)
                self._fallbacks[key] = self._fallbacks.get(key, 0) + \
                    host_rows
            self.device_rows += device_rows
            self.host_rows += host_rows
            self.total_rows += device_rows + host_rows
            self.scans += 1
            total = device_rows + host_rows
            if total:
                self.last_ratio = device_rows / total
                self._registry.set_gauge(DEVICE_COVERAGE_RATIO,
                                         self.last_ratio)

    def absorb(self, tally: ScanTally) -> None:
        """Merge one finished scan tally: batched counter increments,
        per-rule row counts, partial-placement upgrades, and the
        per-scan coverage-ratio gauge."""
        with self._lock:
            for (path, reason), rows in tally.by_reason.items():
                self._registry.inc(HOST_FALLBACK_TOTAL, float(rows),
                                   path=path, reason=reason)
                key = (path, reason)
                self._fallbacks[key] = self._fallbacks.get(key, 0) + rows
            for (policy, rule, path), rows in tally.rule_device.items():
                rec = self._rules.get((policy, rule, path))
                if rec is None:
                    rec = self._upsert(policy, rule, path,
                                       PLACEMENT_DEVICE, None)
                rec['device_rows'] += rows
            for (policy, rule, path, reason) in tally.rule_host:
                rows = tally.rule_host[(policy, rule, path, reason)]
                rec = self._rules.get((policy, rule, path))
                if rec is None:
                    rec = self._upsert(policy, rule, path,
                                       PLACEMENT_DEVICE, None)
                rec['host_rows'] += rows
                self._emit_placement(rec)
            self.device_rows += tally.device_rows
            self.host_rows += tally.host_rows
            self.total_rows += tally.total_rows
            self.scans += 1
            ratio = tally.ratio()
            if ratio is not None:
                self.last_ratio = ratio
                self._registry.set_gauge(DEVICE_COVERAGE_RATIO, ratio)

    # -- reads -------------------------------------------------------------

    def report(self) -> dict:
        """The ``/debug/coverage`` JSON document."""
        with self._lock:
            rules = []
            for key in sorted(self._rules):
                rec = self._rules[key]
                rules.append({
                    'policy': rec['policy'], 'rule': rec['rule'],
                    'path': rec['path'],
                    'placement': rec['placement'],
                    'effective': self._effective(rec),
                    'reason': rec['reason'],
                    'detail': rec['detail'],
                    'device_rows': rec['device_rows'],
                    'host_rows': rec['host_rows'],
                })
            fallbacks: Dict[str, Dict[str, int]] = {}
            for (path, reason), rows in sorted(self._fallbacks.items()):
                fallbacks.setdefault(path, {})[reason] = rows
            return {
                'rules': rules,
                'fallbacks': fallbacks,
                'totals': self._totals_locked(),
            }

    def _totals_locked(self) -> dict:
        total = self.total_rows
        return {
            'device_rows': self.device_rows,
            'host_rows': self.host_rows,
            'total_rows': total,
            'ratio': round(self.device_rows / total, 6) if total else None,
            'scans': self.scans,
            'last_scan_ratio': round(self.last_ratio, 6)
            if self.last_ratio is not None else None,
        }

    def totals(self) -> dict:
        """The ``coverage`` block bench.py embeds in its JSON line."""
        with self._lock:
            out = self._totals_locked()
            by_reason: Dict[str, Dict[str, int]] = {}
            for (path, reason), rows in sorted(self._fallbacks.items()):
                by_reason.setdefault(path, {})[reason] = rows
            out['by_reason'] = by_reason
            return out


# -- module-level no-op-until-configured facade ------------------------------

_ledger: Optional[CoverageLedger] = None


def configure(registry: Optional[MetricsRegistry] = None) -> CoverageLedger:
    """Enable the coverage ledger.  ``registry`` defaults to the
    process-global registry, else a fresh one.  Idempotent;
    :func:`disable` undoes it."""
    global _ledger
    reg = registry or global_registry() or MetricsRegistry()
    _ledger = CoverageLedger(reg)
    return _ledger


def disable() -> None:
    global _ledger
    _ledger = None


def enabled() -> bool:
    return _ledger is not None


def ledger() -> Optional[CoverageLedger]:
    return _ledger


def scan_tally() -> Optional[ScanTally]:
    """A fresh per-scan accumulator, or None when unconfigured (the
    scanner's zero-overhead gate: one attribute read per scan)."""
    led = _ledger
    return ScanTally(led) if led is not None else None


def record_placements(placements: List[RulePlacement]) -> None:
    led = _ledger
    if led is not None:
        led.record_placements(placements)


def record_fallback(path: str, reason: str, policy: str = '',
                    rule: str = '', rows: int = 1) -> None:
    led = _ledger
    if led is not None:
        led.record_fallback(path, reason, policy=policy, rule=rule,
                            rows=rows)


def record_scan(device_rows: int, host_rows: int, path: str = 'validate',
                reason: str = REASON_STATUS_HOST) -> None:
    led = _ledger
    if led is not None:
        led.record_scan(device_rows, host_rows, path=path, reason=reason)


def last_ratio() -> Optional[float]:
    """Device-coverage ratio of the most recently completed scan (what
    the ``device_eval`` span attribute carries), or None."""
    led = _ledger
    return led.last_ratio if led is not None else None


def bench_block() -> Optional[dict]:
    led = _ledger
    return led.totals() if led is not None else None
