"""Observability: metrics instruments, K8s event generation, structured
logging, tracing, and device-pipeline telemetry (reference:
pkg/metrics, pkg/event, pkg/logging, pkg/tracing)."""

from .metrics import MetricsRegistry  # noqa: F401
from .events import EventGenerator  # noqa: F401
from .catalog import METRICS  # noqa: F401
