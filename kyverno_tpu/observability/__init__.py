"""Observability: metrics instruments, K8s event generation, structured
logging (reference: pkg/metrics, pkg/event, pkg/logging)."""

from .metrics import MetricsRegistry  # noqa: F401
from .events import EventGenerator  # noqa: F401
