"""Tracing: OTel-shaped spans over engine rule execution and webhook
handlers (reference: pkg/tracing/config.go NewTraceConfig, span.go,
childspan.go ChildSpan1 wrapping each rule at pkg/engine/validation.go:139;
HTTP handler spans at pkg/webhooks/handlers/trace.go:16).

Design: a process tracer with contextvar span propagation and pluggable
exporters. The in-memory exporter serves tests and the ``/debug/traces``
endpoint; an OTLP-shaped JSON exporter callback can be attached for a
collector — the hermetic environment has no network, so export is a
callable boundary, not a gRPC client.

Tracing is off until :func:`configure` runs (zero overhead: the no-op
tracer allocates nothing per span).
"""

from __future__ import annotations

import contextvars
import os
import secrets
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: exporter failures are counted per exporter class before the
#: exporter is dropped, so a dead exporter is visible on /metrics
#: instead of silently discarding spans
TRACE_EXPORT_ERRORS = 'kyverno_tpu_trace_export_errors_total'

#: consecutive export failures before an exporter is dropped from the
#: tracer (each one already counted on the error series)
EXPORT_FAILURE_LIMIT = 8

_current_span: contextvars.ContextVar[Optional['Span']] = \
    contextvars.ContextVar('ktpu_current_span', default=None)


class Span:
    __slots__ = ('name', 'trace_id', 'span_id', 'parent_id', 'start_ns',
                 'end_ns', 'attributes', 'status', 'status_message',
                 '_tracer', '_token')

    def __init__(self, tracer: 'Tracer', name: str,
                 parent: Optional['Span'],
                 attributes: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = parent.trace_id if parent else secrets.token_hex(16)
        self.span_id = secrets.token_hex(8)
        self.parent_id = parent.span_id if parent else ''
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.status = 'unset'
        self.status_message = ''
        self._tracer = tracer
        self._token = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def set_status(self, status: str, message: str = '') -> None:
        self.status = status
        self.status_message = message

    def record_exception(self, exc: BaseException) -> None:
        self.set_status('error', f'{type(exc).__name__}: {exc}')

    def end(self) -> None:
        self.end_ns = time.time_ns()
        self._tracer._export(self)

    # -- context manager --------------------------------------------------

    def __enter__(self) -> 'Span':
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.record_exception(exc)
        if self._token is not None:
            _current_span.reset(self._token)
        self.end()

    def to_otlp(self) -> dict:
        """OTLP/JSON span shape (subset)."""
        return {
            'traceId': self.trace_id,
            'spanId': self.span_id,
            'parentSpanId': self.parent_id,
            'name': self.name,
            'startTimeUnixNano': str(self.start_ns),
            'endTimeUnixNano': str(self.end_ns),
            'attributes': [
                {'key': k, 'value': {'stringValue': str(v)}}
                for k, v in self.attributes.items()],
            'status': {'code': self.status, 'message': self.status_message},
        }


class _NoopSpan:
    __slots__ = ()

    def set_attribute(self, key, value):
        pass

    def set_status(self, status, message=''):
        pass

    def record_exception(self, exc):
        pass

    def end(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


_NOOP_SPAN = _NoopSpan()


class InMemoryExporter:
    """Bounded ring of finished spans (tests + /debug/traces)."""

    def __init__(self, maxlen: int = 2048):
        import collections
        self._spans = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def __call__(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def find(self, name: str) -> List[Span]:
        return [s for s in self.spans() if s.name == name]

    def children_of(self, span: Span) -> List[Span]:
        return [s for s in self.spans() if s.parent_id == span.span_id]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


class JsonlExporter:
    """Append each finished span as one OTLP-shaped JSON line.

    Serves the bench path: a scan run leaves a machine-readable
    per-stage record on disk (``stage_breakdown`` assembly) without a
    collector.  Writes are line-buffered and locked.  The file rotates
    by size (``KTPU_TRACE_JSONL_MAX_BYTES``; 0 disables): when the next
    line would exceed the budget, the current file moves to
    ``<path>.1`` (one rotated generation kept) and a fresh file opens —
    long benches no longer grow the trace file without bound.  A write
    failure closes the exporter and re-raises so ``Tracer._export``
    counts it on ``kyverno_tpu_trace_export_errors_total``."""

    DEFAULT_MAX_BYTES = 64 << 20

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get(
                    'KTPU_TRACE_JSONL_MAX_BYTES',
                    str(self.DEFAULT_MAX_BYTES)))
            except ValueError:
                max_bytes = self.DEFAULT_MAX_BYTES
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._file = open(path, 'a', buffering=1)
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0

    def __call__(self, span: Span) -> None:
        with self._lock:
            if self._file is None:
                return
            import json
            line = json.dumps(span.to_otlp()) + '\n'
            try:
                if self.max_bytes > 0 and \
                        self._bytes + len(line) > self.max_bytes:
                    self._rotate()
                self._file.write(line)
                self._bytes += len(line)
            except (OSError, ValueError):
                self.close()
                raise

    def _rotate(self) -> None:
        """Current file → ``<path>.1`` (replacing any prior rotation),
        then reopen fresh.  Called under the lock."""
        f, self._file = self._file, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        os.replace(self.path, self.path + '.1')
        self._file = open(self.path, 'a', buffering=1)
        self._bytes = 0

    def close(self) -> None:
        f, self._file = self._file, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


class Tracer:
    """reference: pkg/tracing — StartSpan/ChildSpan equivalents."""

    def __init__(self, exporters: Optional[List[Callable[[Span], None]]]
                 = None, enabled: bool = True):
        self.exporters = exporters or []
        self.enabled = enabled
        # consecutive failures per exporter (id-keyed; reset on any
        # successful export) — drives the drop-after-N policy
        self._export_failures: Dict[int, int] = {}

    def start_span(self, name: str,
                   attributes: Optional[Dict[str, Any]] = None,
                   parent: Optional[Span] = None):
        """Child of the context's current span (childspan.go ChildSpan1).
        ``parent`` overrides the contextvar — pipeline stages running on
        worker threads pass the request span captured at scan entry so
        one trace covers request → device → report."""
        if not self.enabled:
            return _NOOP_SPAN
        return Span(self, name, parent if parent is not None
                    else _current_span.get(), attributes)

    def _export(self, span: Span) -> None:
        for exporter in list(self.exporters):
            try:
                exporter(span)
            except Exception:  # noqa: BLE001 - exporters must not break
                self._count_export_error(exporter)
            else:
                if self._export_failures:
                    self._export_failures.pop(id(exporter), None)

    def _count_export_error(self, exporter) -> None:
        """A span exporter raised: count it (so a dead exporter shows
        on /metrics) and drop the exporter after EXPORT_FAILURE_LIMIT
        consecutive failures instead of burning a raise per span."""
        from .metrics import global_registry
        registry = global_registry()
        if registry is not None:
            registry.inc(TRACE_EXPORT_ERRORS,
                         exporter=type(exporter).__name__)
        n = self._export_failures.get(id(exporter), 0) + 1
        self._export_failures[id(exporter)] = n
        if n >= EXPORT_FAILURE_LIMIT:
            try:
                self.exporters.remove(exporter)
            except ValueError:
                pass
            self._export_failures.pop(id(exporter), None)


_NOOP_TRACER = Tracer(enabled=False)
_tracer: Tracer = _NOOP_TRACER
_memory: Optional[InMemoryExporter] = None


def configure(otlp_exporter: Optional[Callable[[Span], None]] = None,
              memory: bool = True,
              jsonl_path: Optional[str] = None) -> Optional[InMemoryExporter]:
    """Enable tracing (flag parity: cmd/internal/flag.go:46-49
    enableTracing/tracingAddress). Returns the in-memory exporter."""
    global _tracer, _memory
    exporters: List[Callable[[Span], None]] = []
    if memory:
        _memory = InMemoryExporter()
        exporters.append(_memory)
    if otlp_exporter is not None:
        exporters.append(otlp_exporter)
    if jsonl_path is not None:
        exporters.append(JsonlExporter(jsonl_path))
    _tracer = Tracer(exporters)
    return _memory


def disable() -> None:
    global _tracer, _memory
    for exporter in _tracer.exporters:
        close = getattr(exporter, 'close', None)
        if close is not None:
            close()
    _tracer = _NOOP_TRACER
    _memory = None


def tracer() -> Tracer:
    return _tracer


def memory_exporter() -> Optional[InMemoryExporter]:
    return _memory


def start_span(name: str, attributes: Optional[Dict[str, Any]] = None):
    # ktpu: noqa[KTPU504] -- forwarder: span names are checked against
    # the catalog at each caller's site, not at this pass-through
    return _tracer.start_span(name, attributes)


def current_span():
    return _current_span.get()


class _SpanScope:
    """Make an existing span the ambient parent on this thread without
    touching its lifecycle (the owner still ends it)."""

    __slots__ = ('span', '_token')

    def __init__(self, span: Optional[Span]):
        self.span = span
        self._token = None

    def __enter__(self):
        if self.span is not None:
            self._token = _current_span.set(self.span)
        return self.span

    def __exit__(self, *exc):
        if self._token is not None:
            _current_span.reset(self._token)
        return False


def install_span(span: Optional[Span]) -> _SpanScope:
    """Context manager parenting this thread's new spans under ``span``
    (no-op for None).  Pipeline worker threads install the scan's
    request span so every stage span joins one trace — the span itself
    is neither entered nor ended here."""
    return _SpanScope(span)
