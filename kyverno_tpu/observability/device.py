"""Device-pipeline telemetry: stage spans, TPU metrics, d2h watchdog.

The batched scan path (``compiler/scan.py`` + ``ops/eval.py``) runs as
a pipeline — pack-plan build, host feature extraction (encode), h2d
transfer, XLA trace/compile, device eval dispatch, d2h readback, report
assembly.  This module gives each stage an OTel-shaped child span (via
``observability.tracing``) and a matching Prometheus series
(``kyverno_tpu_scan_stage_duration_seconds{stage=...}``), plus cache
hit/miss counters and a **d2h stall watchdog**: a monitor thread that
fires a structured event, an ERROR log line, and a
``kyverno_tpu_d2h_stalls_total`` increment whenever a device→host
readback blocks longer than ``KTPU_D2H_STALL_S`` (default 30s) — the
remote-tunnel stalls dominating streaming throughput finally leave a
trace instead of silently starving the pipeline.

Everything here is a no-op until :func:`configure` runs (and spans
additionally require ``tracing.configure``): unconfigured processes
allocate no spans, create no series, and start no threads, so tier-1
timings and bit-identical PolicyReport output are unaffected.
"""

from __future__ import annotations

import collections
import contextvars
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import tracing
from .metrics import (WIDE_BUCKETS, MetricsRegistry, global_registry)

SCAN_STAGE_DURATION = 'kyverno_tpu_scan_stage_duration_seconds'
COMPILE_CACHE_REQUESTS = 'kyverno_tpu_compile_cache_requests_total'
DEVICE_BATCH_SIZE = 'kyverno_tpu_device_batch_size'
D2H_BYTES = 'kyverno_tpu_d2h_bytes_total'
D2H_STALLS = 'kyverno_tpu_d2h_stalls_total'
PIPELINE_INFLIGHT = 'kyverno_tpu_scan_pipeline_inflight_chunks'
BACKPRESSURE = 'kyverno_tpu_scan_backpressure_seconds_total'

#: canonical stage labels, in pipeline order
STAGES = ('pack', 'encode', 'h2d', 'compile', 'device_eval', 'd2h',
          'report')

_log = logging.getLogger('kyverno.device')

_registry: Optional[MetricsRegistry] = None
_watchdog: Optional['D2HWatchdog'] = None
_event_sink: Optional[Callable[[dict], None]] = None
#: additional watchdog-event listeners (the flight recorder registers
#: its dump trigger here); independent of configure()'s event_sink so
#: provenance and a caller-supplied sink compose
_extra_sinks: List[Callable[[dict], None]] = []


def add_event_sink(fn: Callable[[dict], None]) -> None:
    if fn not in _extra_sinks:
        _extra_sinks.append(fn)


def remove_event_sink(fn: Callable[[dict], None]) -> None:
    try:
        _extra_sinks.remove(fn)
    except ValueError:
        pass


def _stall_threshold_default() -> float:
    try:
        return float(os.environ.get('KTPU_D2H_STALL_S', '30'))
    except ValueError:
        return 30.0


def configure(registry: Optional[MetricsRegistry] = None,
              stall_threshold_s: Optional[float] = None,
              event_sink: Optional[Callable[[dict], None]] = None
              ) -> MetricsRegistry:
    """Enable device-pipeline metrics (and the stall watchdog).

    ``registry`` defaults to the process-global registry, else a fresh
    one.  Returns the registry in use.  Idempotent; ``disable`` undoes
    it (and stops the watchdog thread)."""
    global _registry, _watchdog, _event_sink
    reg = registry or global_registry() or MetricsRegistry()
    reg.register_histogram(SCAN_STAGE_DURATION, WIDE_BUCKETS)
    # in-flight chunks is a residency gauge: once the pipeline drains
    # it must export 0 (swept by cmd/internal.Setup.shutdown)
    reg.mark_reset_on_close(PIPELINE_INFLIGHT)
    _event_sink = event_sink
    threshold = stall_threshold_s if stall_threshold_s is not None \
        else _stall_threshold_default()
    if _watchdog is not None:
        _watchdog.stop()
    _watchdog = D2HWatchdog(threshold)
    _registry = reg
    return reg


def disable() -> None:
    global _registry, _watchdog, _event_sink
    wd, _watchdog = _watchdog, None
    _registry = None
    _event_sink = None
    if wd is not None:
        wd.stop()


def registry() -> Optional[MetricsRegistry]:
    return _registry


def watchdog() -> Optional['D2HWatchdog']:
    return _watchdog


def enabled() -> bool:
    """True when any instrumentation would record (metrics configured
    or tracing on) — the zero-overhead gate for the scan hot path."""
    return _registry is not None or tracing.tracer().enabled


# -- per-scan capture -------------------------------------------------------

#: the decision-provenance accumulator for the scan running on this
#: thread/context (None almost always — one contextvar read per stage)
_capture_var: contextvars.ContextVar[Optional['ScanCapture']] = \
    contextvars.ContextVar('ktpu_scan_capture', default=None)


class ScanCapture:
    """Per-scan stage-time accumulator for decision provenance:
    installed around one ``scanner.scan`` / ``scan_report_results``
    call, it collects the scan's own stage durations (``device_eval``
    drives the amortized per-rider device-time share), the AOT
    executable-cache outcome, and the scan's device-coverage ratio —
    without attributing concurrent scans' stages to each other the way
    a registry-sum delta would."""

    __slots__ = ('stages', 'aot', 'coverage_ratio', 'critical_path',
                 '_lock')

    def __init__(self):
        self.stages: Dict[str, float] = {}
        self.aot = ''
        self.coverage_ratio: Optional[float] = None
        #: critical-path blame summary for this scan, filled by the
        #: timeline recorder (observability/timeline.py) when armed
        self.critical_path: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:
            self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    def stage_s(self, stage: str) -> float:
        with self._lock:
            return self.stages.get(stage, 0.0)


class _CaptureScope:
    __slots__ = ('capture', '_token')

    def __init__(self, capture: Optional[ScanCapture]):
        self.capture = capture
        self._token = None

    def __enter__(self) -> Optional[ScanCapture]:
        if self.capture is not None:
            self._token = _capture_var.set(self.capture)
        return self.capture

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _capture_var.reset(self._token)


def install_capture(capture: Optional[ScanCapture]) -> _CaptureScope:
    """Context manager making ``capture`` the ambient scan accumulator
    (no-op for None).  The scan pipeline re-installs it on its worker
    threads (``compiler/scan.py`` encode/dispatch closures), the same
    way stage spans re-parent through ``tel_parent``."""
    return _CaptureScope(capture)


def current_capture() -> Optional[ScanCapture]:
    return _capture_var.get()


def merge_worker_stages(stages: Dict[str, float]) -> None:
    """Fold stage seconds measured inside a forked encode worker into
    the parent's telemetry: the stage histogram and the ambient
    ScanCapture.  Worker processes inherit telemetry globals at fork
    but their metric increments and contextvars die with them — the
    measured times ride home with the encoded tensors and are
    re-attributed here, on the pipeline thread that resolved them."""
    if not stages:
        return
    capture = _capture_var.get()
    for name, seconds in stages.items():
        if _registry is not None:
            _registry.observe(SCAN_STAGE_DURATION, seconds, stage=name)
        if capture is not None:
            capture.add(name, seconds)


# -- stage timers -----------------------------------------------------------

class _NoopStage:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attribute(self, key, value):
        pass

    def add_d2h_bytes(self, n):
        pass


_NOOP_STAGE = _NoopStage()


class _Stage:
    __slots__ = ('stage', 'span', '_t0', '_capture')

    def __init__(self, stage: str, span, t0: float, capture=None):
        self.stage = stage
        self.span = span
        self._t0 = t0
        self._capture = capture

    def set_attribute(self, key, value):
        self.span.set_attribute(key, value)

    def add_d2h_bytes(self, n: int) -> None:
        add_d2h_bytes(n)

    def __enter__(self):
        self.span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.span.__exit__(exc_type, exc, tb)
        elapsed = time.monotonic() - self._t0
        if _registry is not None:
            _registry.observe(SCAN_STAGE_DURATION, elapsed,
                              stage=self.stage)
        if self._capture is not None:
            self._capture.add(self.stage, elapsed)
        return False


def stage(name: str, attributes: Optional[Dict[str, Any]] = None,
          parent=None):
    """Context manager timing one pipeline stage: a
    ``kyverno/device/<name>`` span (child of ``parent`` or the context
    span) plus a stage-labelled histogram sample (and a line in the
    active provenance ScanCapture, when one is installed).  Returns a
    shared no-op when telemetry is unconfigured."""
    capture = _capture_var.get()
    if _registry is None and capture is None and \
            not tracing.tracer().enabled:
        return _NOOP_STAGE
    span = tracing.tracer().start_span(f'kyverno/device/{name}',
                                       attributes, parent=parent)
    return _Stage(name, span, time.monotonic(), capture)


# -- counters / gauges ------------------------------------------------------

def record_cache(result: str) -> None:
    """Executable-cache outcome: hit | miss | aot_load | aot_store."""
    if _registry is not None:
        _registry.inc(COMPILE_CACHE_REQUESTS, result=result)
    capture = _capture_var.get()
    if capture is not None and result != 'aot_store':
        # the scan's lookup outcome (aot_store is the async write-back
        # that follows a miss, not a distinct lookup result)
        capture.aot = result


def set_batch_size(n: int) -> None:
    if _registry is not None:
        # ktpu: noqa[KTPU603] -- the canonical batch capacity is
        # configuration, not occupancy; it stays meaningful after a
        # drain and resetting it to 0 would misreport the shape table
        _registry.set_gauge(DEVICE_BATCH_SIZE, float(n))


def add_d2h_bytes(n: int) -> None:
    if _registry is not None and n:
        _registry.inc(D2H_BYTES, float(n))


def set_pipeline_inflight(n: int) -> None:
    """Chunks currently resident in the streaming scan pipeline
    (bounded by KTPU_PIPELINE_DEPTH; reset to 0 when a scan ends)."""
    if _registry is not None:
        _registry.set_gauge(PIPELINE_INFLIGHT, float(n))


def add_backpressure(stage: str, seconds: float) -> None:
    """Time a pipeline stage spent blocked handing its chunk to a full
    downstream queue (or the intake waiting for a free chunk slot) —
    the direct measure of which leg bounds the stream."""
    if _registry is not None and seconds > 0:
        _registry.inc(BACKPRESSURE, float(seconds), stage=stage)


# -- d2h stall watchdog -----------------------------------------------------

class D2HWatchdog:
    """Monitor thread flagging device→host readbacks that exceed a
    threshold.  ``arm`` registers a readback; if it is still armed past
    its deadline the watchdog fires ONCE for it: structured event +
    ERROR log line + ``kyverno_tpu_d2h_stalls_total`` increment.  The
    thread starts lazily on the first ``arm`` and exits on ``stop`` —
    an unconfigured or idle process runs no thread."""

    def __init__(self, threshold_s: float):
        self.threshold_s = threshold_s
        self._cv = threading.Condition()
        self._entries: Dict[int, list] = {}  # token -> [start, attrs, fired]
        self._seq = 0
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self.stall_events: 'collections.deque[dict]' = \
            collections.deque(maxlen=256)

    def arm(self, attrs: Optional[Dict[str, Any]] = None) -> int:
        with self._cv:
            if self._stopped:
                return -1
            token = self._seq
            self._seq += 1
            self._entries[token] = [time.monotonic(), dict(attrs or {}),
                                    False]
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name='ktpu-d2h-watchdog',
                    daemon=True)
                self._thread.start()
            self._cv.notify()
        return token

    def disarm(self, token: int) -> float:
        with self._cv:
            entry = self._entries.pop(token, None)
        if entry is None:
            return 0.0
        return time.monotonic() - entry[0]

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._entries.clear()
            self._cv.notify()
            t = self._thread
        if t is not None:
            t.join(timeout=2)
            # arm() reads/writes _thread under the condition variable;
            # clearing it outside raced a concurrent arm (join must
            # stay outside — _run holds the cv between waits)
            with self._cv:
                self._thread = None

    def _run(self) -> None:
        with self._cv:
            while not self._stopped:
                now = time.monotonic()
                next_deadline: Optional[float] = None
                for entry in self._entries.values():
                    start, attrs, fired = entry
                    if fired:
                        continue
                    deadline = start + self.threshold_s
                    if deadline <= now:
                        entry[2] = True
                        self._fire(now - start, attrs)
                    elif next_deadline is None or deadline < next_deadline:
                        next_deadline = deadline
                timeout = None if next_deadline is None \
                    else max(next_deadline - now, 0.01)
                self._cv.wait(timeout)

    def _fire(self, elapsed_s: float, attrs: Dict[str, Any]) -> None:
        event = {
            'type': 'd2h_stall',
            'threshold_s': self.threshold_s,
            'elapsed_s': round(elapsed_s, 3),
            'ts': time.time(),
            **attrs,
        }
        self.stall_events.append(event)
        if _registry is not None:
            _registry.inc(D2H_STALLS)
        from .logging import with_values
        with_values(_log, 'd2h readback stalled', level=logging.ERROR,
                    **{k: v for k, v in event.items() if k != 'type'})
        sinks = ([_event_sink] if _event_sink is not None else []) \
            + list(_extra_sinks)
        for sink in sinks:
            try:
                sink(event)
            except Exception:  # noqa: BLE001 - sinks must not break d2h
                pass


class _D2HGuard:
    """Stage timer for a readback with the watchdog armed around it."""

    __slots__ = ('_stage', '_token')

    def __init__(self, stage_cm, token: int):
        self._stage = stage_cm
        self._token = token

    def set_attribute(self, key, value):
        self._stage.set_attribute(key, value)

    def add_d2h_bytes(self, n: int) -> None:
        add_d2h_bytes(n)

    def __enter__(self):
        self._stage.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        wd = _watchdog
        if wd is not None and self._token >= 0:
            wd.disarm(self._token)
        return self._stage.__exit__(exc_type, exc, tb)


def d2h_guard(attributes: Optional[Dict[str, Any]] = None, parent=None):
    """``stage('d2h')`` with the stall watchdog armed for its duration."""
    if _registry is None and _capture_var.get() is None and \
            not tracing.tracer().enabled:
        return _NOOP_STAGE
    token = _watchdog.arm(attributes) if _watchdog is not None else -1
    return _D2HGuard(stage('d2h', attributes, parent=parent), token)


def stage_breakdown() -> Dict[str, Dict[str, float]]:
    """Per-stage {total_s, count, mean_s} from the stage histogram —
    the ``stage_breakdown`` block bench.py embeds in its JSON line."""
    if _registry is None:
        return {}
    out: Dict[str, Dict[str, float]] = {}
    for key, count, total in _registry.histogram_series(
            SCAN_STAGE_DURATION):
        labels = dict(key)
        stage_name = labels.get('stage', '')
        out[stage_name] = {
            'total_s': round(total, 4),
            'count': count,
            'mean_s': round(total / count, 6) if count else 0.0,
        }
    return out
