"""Profiling endpoint (reference: pkg/profiling/pprof.go; flags
-profile / -profilePort=6060 at cmd/internal/flag.go:40-42).

Python equivalent of Go's net/http/pprof surface:

* ``/debug/pprof/`` — index
* ``/debug/pprof/goroutine`` — all live thread stacks (Go's goroutine
  profile analogue), plain text
* ``/debug/pprof/profile?seconds=N`` — sampling CPU profile: stacks of
  every thread sampled at ~100 Hz for N seconds, returned as folded
  stacks (``frame;frame;frame count`` lines — flamegraph-ready)
* ``/debug/traces`` — recent spans from the in-memory trace exporter as
  OTLP-shaped JSON (``?limit=N`` bounds the response, ``?trace_id=...``
  narrows to one trace)
* ``/debug/decisions`` — the decision-provenance flight recorder: last
  N DecisionRecords + the error/shed ring (``?limit=N``)
* ``/debug/coverage`` — the device-coverage ledger (per-rule placement,
  attributed host-fallback counts) as JSON
* ``/debug/breakers`` — live circuit-breaker state per policy set
  (state machine position, failure/trip counts, reopen countdowns) as
  JSON
* ``/metrics`` — Prometheus text exposition of the active registry
"""

from __future__ import annotations

import json
import sys
import threading
import time
import traceback
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


def thread_stacks() -> str:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f'thread {tid} ({names.get(tid, "?")}):')
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
        out.append('')
    return '\n'.join(out)


def sample_profile(seconds: float, hz: int = 100) -> str:
    """Folded-stacks sampling profile across all threads."""
    counts: Counter = Counter()
    deadline = time.time() + seconds
    interval = 1.0 / hz
    me = threading.get_ident()
    while time.time() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            frames = []
            f = frame
            while f is not None:
                code = f.f_code
                frames.append(f'{code.co_name} '
                              f'({code.co_filename.rsplit("/", 1)[-1]}:'
                              f'{f.f_lineno})')
                f = f.f_back
            counts[';'.join(reversed(frames))] += 1
        time.sleep(interval)
    return '\n'.join(f'{stack} {n}'
                     for stack, n in counts.most_common()) or '(idle)\n'


class ProfilingServer:
    """reference: pkg/profiling/pprof.go — starts only with -profile."""

    def __init__(self, port: int = 6060):
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 - quiet
                pass

            def _send(self, body: str, ctype='text/plain', code=200):
                data = body.encode()
                self.send_response(code)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                parsed = urlparse(self.path)
                if parsed.path in ('/debug/pprof', '/debug/pprof/'):
                    self._send('profiles:\n  goroutine\n  profile\n'
                               '  traces\n  decisions\n  coverage\n')
                elif parsed.path == '/debug/pprof/goroutine':
                    self._send(thread_stacks())
                elif parsed.path == '/debug/pprof/profile':
                    q = parse_qs(parsed.query)
                    try:
                        seconds = float(q.get('seconds', ['1'])[0])
                    except ValueError:
                        self._send('bad seconds parameter', code=400)
                        return
                    self._send(sample_profile(min(max(seconds, 0.01),
                                                  60.0)))
                elif parsed.path == '/debug/traces':
                    from . import tracing
                    mem = tracing.memory_exporter()
                    spans = mem.spans() if mem is not None else []
                    q = parse_qs(parsed.query)
                    # ?trace_id= narrows to one trace, ?limit=N bounds
                    # the response to the most recent N — flight-
                    # recorder follow-ups fetch one decision's spans
                    # instead of paging the whole ring
                    trace_id = q.get('trace_id', [''])[0]
                    if trace_id:
                        spans = [s for s in spans
                                 if s.trace_id == trace_id]
                    try:
                        limit = int(q.get('limit', ['0'])[0])
                    except ValueError:
                        self._send('bad limit parameter', code=400)
                        return
                    if limit > 0:
                        spans = spans[-limit:]
                    self._send(json.dumps(
                        {'spans': [s.to_otlp() for s in spans]}),
                        'application/json')
                elif parsed.path == '/debug/decisions':
                    from . import provenance
                    rec = provenance.recorder()
                    if rec is None:
                        self._send(json.dumps({'enabled': False}),
                                   'application/json')
                        return
                    q = parse_qs(parsed.query)
                    try:
                        limit = int(q.get('limit', ['0'])[0]) or None
                    except ValueError:
                        self._send('bad limit parameter', code=400)
                        return
                    body = {
                        'enabled': True,
                        'stats': rec.stats(),
                        'decisions': [r.to_dict()
                                      for r in rec.records(limit)],
                        'errors': [r.to_dict()
                                   for r in rec.errors(limit)],
                    }
                    self._send(json.dumps(body), 'application/json')
                elif parsed.path == '/debug/coverage':
                    from . import coverage
                    led = coverage.ledger()
                    body = dict(led.report(), enabled=True) \
                        if led is not None else {'enabled': False}
                    self._send(json.dumps(body), 'application/json')
                elif parsed.path == '/debug/breakers':
                    from ..serving import breaker as breaker_mod
                    self._send(json.dumps(breaker_mod.debug_report()),
                               'application/json')
                elif parsed.path == '/metrics':
                    from . import device
                    from .metrics import global_registry
                    reg = device.registry() or global_registry()
                    self._send(reg.render() if reg is not None else '',
                               'text/plain; version=0.0.4')
                else:
                    self._send('not found', code=404)

        self._httpd = ThreadingHTTPServer(('127.0.0.1', self.port),
                                          _Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name='ktpu-profiling', daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
