"""Profiling endpoint (reference: pkg/profiling/pprof.go; flags
-profile / -profilePort=6060 at cmd/internal/flag.go:40-42).

Python equivalent of Go's net/http/pprof surface, plus the repo's own
debug routes.  Every route **self-registers** through
:func:`debug_route` into one table that drives four consumers — the
HTTP dispatch, the ``GET /debug/`` index, the 404-with-index response
for unknown ``/debug/*`` paths, and the README endpoint table
(``python scripts/analyze.py --debug-table``, drift-checked by
``tests/test_profiling_endpoints.py``) — so there is no hand-maintained
route list anywhere.

:func:`deep_profile` captures an on-demand deep profile: the Python
sampling profiler (folded stacks) plus a ``jax.profiler.trace`` when a
device backend is already live, written under a bounded artifact
directory (``KTPU_PROFILE_DIR``, last :data:`PROFILE_KEEP` captures
kept).  Served at ``GET /debug/profile?seconds=N`` and auto-fired by
the SLO engine when burn rate degrades (``observability/slo.py``).
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import sys
import threading
import time
import traceback
from collections import Counter, OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple
from urllib.parse import parse_qs, urlparse

#: auto-captures beyond this count evict the oldest artifact directory
PROFILE_KEEP = 8

_profile_seq = itertools.count(1)
_profile_lock = threading.Lock()


def thread_stacks() -> str:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f'thread {tid} ({names.get(tid, "?")}):')
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
        out.append('')
    return '\n'.join(out)


def sample_profile(seconds: float, hz: int = 100) -> str:
    """Folded-stacks sampling profile across all threads."""
    counts: Counter = Counter()
    deadline = time.time() + seconds
    interval = 1.0 / hz
    me = threading.get_ident()
    while time.time() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            frames = []
            f = frame
            while f is not None:
                code = f.f_code
                frames.append(f'{code.co_name} '
                              f'({code.co_filename.rsplit("/", 1)[-1]}:'
                              f'{f.f_lineno})')
                f = f.f_back
            counts[';'.join(reversed(frames))] += 1
        time.sleep(interval)
    return '\n'.join(f'{stack} {n}'
                     for stack, n in counts.most_common()) or '(idle)\n'


# -- deep profile capture ----------------------------------------------------

def _env_profile_dir() -> str:
    return os.environ.get(
        'KTPU_PROFILE_DIR',
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), '.cache', 'profiles'))


def _jax_backend_live() -> bool:
    """True only when jax is imported AND a backend is already
    initialized — deep_profile must never be the thing that pays (or
    hangs on) backend bring-up."""
    if 'jax' not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge
        return bool(getattr(xla_bridge, '_backends', None))
    except Exception:  # noqa: BLE001 - private API moved: skip trace
        return False


def _prune_profiles(root: str) -> None:
    """Keep the newest PROFILE_KEEP capture dirs (bounded artifacts —
    a burn-rate flap cannot fill the disk)."""
    try:
        entries = [e for e in os.scandir(root)
                   if e.is_dir() and e.name.startswith('profile-')]
    except OSError:
        return
    entries.sort(key=lambda e: e.stat().st_mtime)
    for entry in entries[:-PROFILE_KEEP]:
        shutil.rmtree(entry.path, ignore_errors=True)


def deep_profile(seconds: float = 2.0, trigger: str = 'manual',
                 out_dir: Optional[str] = None) -> dict:
    """Capture a deep profile: ``py.folded`` (sampling profiler, all
    threads) always; a ``jax/`` profiler trace when a device backend is
    live.  Artifacts land under ``<KTPU_PROFILE_DIR>/profile-<trigger>-
    <pid>-<n>/``; the directory is bounded to :data:`PROFILE_KEEP`
    captures.  Serialized per process (one capture at a time) — callers
    block for ``seconds``."""
    seconds = min(max(seconds, 0.01), 60.0)
    root = out_dir or _env_profile_dir()
    path = os.path.join(
        root, f'profile-{trigger}-{os.getpid()}-{next(_profile_seq)}')
    with _profile_lock:
        os.makedirs(path, exist_ok=True)
        artifacts: List[str] = []
        jax_traced = False
        if _jax_backend_live():
            try:
                import jax
                jax.profiler.start_trace(os.path.join(path, 'jax'))
                jax_traced = True
            except Exception:  # noqa: BLE001 - py profile still lands
                jax_traced = False
        folded = sample_profile(seconds)
        if jax_traced:
            try:
                import jax
                jax.profiler.stop_trace()
                artifacts.append('jax')
            except Exception:  # noqa: BLE001
                jax_traced = False
        with open(os.path.join(path, 'py.folded'), 'w') as f:
            f.write(folded)
        artifacts.append('py.folded')
        _prune_profiles(root)
    return {'dir': path, 'seconds': seconds, 'trigger': trigger,
            'jax_trace': jax_traced, 'artifacts': artifacts}


# -- debug route registry ----------------------------------------------------

class _Route(NamedTuple):
    path: str
    help: str
    fn: Callable[[Dict[str, List[str]]], Tuple[str, str, int]]


#: path → route, in registration order; the single source the HTTP
#: dispatch, /debug/ index, and README table all read
_ROUTES: 'OrderedDict[str, _Route]' = OrderedDict()


def debug_route(path: str, help: str):  # noqa: A002 - table DSL
    """Register ``fn(query) -> (body, content_type, status)`` as the
    handler for ``path`` on the profiling server."""
    def deco(fn):
        _ROUTES[path] = _Route(path, help, fn)
        return fn
    return deco


def routes() -> Dict[str, Tuple[str, str]]:
    """path → (help,) view for the index endpoint and tests."""
    return {r.path: (r.help,) for r in _ROUTES.values()}


def render_debug_index() -> str:
    """The ``GET /debug/`` body: every registered route, one line of
    help each (also the 404 body for unknown ``/debug/*`` paths)."""
    width = max(len(p) for p in _ROUTES) + 2
    lines = ['debug endpoints:', '']
    for path in sorted(_ROUTES):
        lines.append(f'  {path:<{width}}{_ROUTES[path].help}')
    return '\n'.join(lines) + '\n'


def render_debug_table() -> str:
    """The README endpoint table, generated so docs cannot drift from
    the registry (same contract as the knob table)."""
    rows = ['| Endpoint | Returns |', '|---|---|']
    for path in sorted(_ROUTES):
        rows.append(f'| `{path}` | {_ROUTES[path].help} |')
    return '\n'.join(rows)


def _bad_param(name: str) -> Tuple[str, str, int]:
    return f'bad {name} parameter', 'text/plain', 400


def _json_body(obj) -> Tuple[str, str, int]:
    return json.dumps(obj), 'application/json', 200


# -- routes ------------------------------------------------------------------

@debug_route('/debug/pprof', 'pprof profile index.')
def _r_pprof(query):
    return ('profiles:\n  goroutine\n  profile\n'
            '  traces\n  decisions\n  coverage\n', 'text/plain', 200)


@debug_route('/debug/pprof/goroutine',
             'All live thread stacks (goroutine profile analogue), '
             'plain text.')
def _r_goroutine(query):
    return thread_stacks(), 'text/plain', 200


@debug_route('/debug/pprof/profile',
             'Sampling CPU profile as folded stacks '
             '(`?seconds=N`, clamped to 60s).')
def _r_profile(query):
    try:
        seconds = float(query.get('seconds', ['1'])[0])
    except ValueError:
        return _bad_param('seconds')
    return (sample_profile(min(max(seconds, 0.01), 60.0)),
            'text/plain', 200)


@debug_route('/debug/traces',
             'Recent spans from the in-memory trace exporter as '
             'OTLP-shaped JSON (`?limit=N`, `?trace_id=...`).')
def _r_traces(query):
    from . import tracing
    mem = tracing.memory_exporter()
    spans = mem.spans() if mem is not None else []
    # ?trace_id= narrows to one trace, ?limit=N bounds the response to
    # the most recent N — flight-recorder follow-ups fetch one
    # decision's spans instead of paging the whole ring
    trace_id = query.get('trace_id', [''])[0]
    if trace_id:
        spans = [s for s in spans if s.trace_id == trace_id]
    try:
        limit = int(query.get('limit', ['0'])[0])
    except ValueError:
        return _bad_param('limit')
    if limit > 0:
        spans = spans[-limit:]
    return _json_body({'spans': [s.to_otlp() for s in spans]})


@debug_route('/debug/decisions',
             'Decision-provenance flight recorder: last N '
             'DecisionRecords + the error/shed ring (`?limit=N`).')
def _r_decisions(query):
    from . import provenance
    rec = provenance.recorder()
    if rec is None:
        return _json_body({'enabled': False})
    try:
        limit = int(query.get('limit', ['0'])[0]) or None
    except ValueError:
        return _bad_param('limit')
    return _json_body({
        'enabled': True,
        'stats': rec.stats(),
        'decisions': [r.to_dict() for r in rec.records(limit)],
        'errors': [r.to_dict() for r in rec.errors(limit)],
    })


@debug_route('/debug/coverage',
             'Device-coverage ledger: per-rule placement + attributed '
             'host-fallback counts, JSON.')
def _r_coverage(query):
    from . import coverage
    led = coverage.ledger()
    body = dict(led.report(), enabled=True) \
        if led is not None else {'enabled': False}
    return _json_body(body)


@debug_route('/debug/breakers',
             'Live circuit-breaker state per policy set (state '
             'machine position, failure/trip counts), JSON.')
def _r_breakers(query):
    from ..serving import breaker as breaker_mod
    return _json_body(breaker_mod.debug_report())


@debug_route('/debug/executables',
             'Executable lifecycle ledger: every compiled program '
             'with source, build cost, dispatch/device-time totals '
             '(JSON; `?format=table` for a terminal view).')
def _r_executables(query):
    from . import executables
    led = executables.ledger()
    if led is None:
        return _json_body({'enabled': False})
    if query.get('format', [''])[0] == 'table':
        return led.render_table(), 'text/plain', 200
    return _json_body(led.report())


@debug_route('/debug/partitions',
             'Partitioned-compilation census: per-partition member/'
             'fingerprint/executable attribution for each live plan '
             'plus the recent hot-swap log, JSON.')
def _r_partitions(query):
    from ..partition import census
    return _json_body(census.report())


@debug_route('/debug/slo',
             'Serving SLO state: burn rates, budget remaining, '
             'per-path windowed latency digests, JSON.')
def _r_slo(query):
    from . import slo
    eng = slo.engine()
    if eng is None:
        return _json_body({'enabled': False})
    return _json_body(dict(eng.snapshot(), enabled=True))


@debug_route('/debug/fleet',
             'Fleet observatory: merged cross-host metric federation, '
             'per-process snapshots and the mesh skew verdict (JSON; '
             '`?format=table` for a terminal view).')
def _r_fleet(query):
    from . import fleet
    fr = fleet.fleet()
    if fr is None:
        return _json_body({'enabled': False})
    if query.get('format', [''])[0] == 'table':
        return fr.render_table(), 'text/plain', 200
    return _json_body(fr.report())


@debug_route('/debug/profile',
             'On-demand deep profile (`?seconds=N`, clamped to 60s): '
             'py sampling profile + jax trace when a backend is live; '
             'artifacts under KTPU_PROFILE_DIR, JSON summary.')
def _r_deep_profile(query):
    try:
        seconds = float(query.get('seconds', ['2'])[0])
    except ValueError:
        return _bad_param('seconds')
    return _json_body(deep_profile(seconds=seconds, trigger='manual'))


@debug_route('/debug/timeline',
             'Pipeline critical-path observatory: per-scan blame '
             'summaries + cumulative stage blame (JSON; '
             '`?format=chrome` exports the recent-scan timelines as '
             'Chrome trace-event JSON — load it in Perfetto).')
def _r_timeline(query):
    from . import timeline
    rec = timeline.recorder()
    if rec is None:
        return _json_body({'enabled': False})
    if query.get('format', [''])[0] == 'chrome':
        return _json_body(rec.chrome_trace())
    return _json_body({
        'enabled': True,
        'scans': rec.n_scans,
        'last': rec.last_summary,
        'blame_totals_s': {s: round(v, 6)
                           for s, v in rec.blame_totals().items()},
        'wall_total_s': round(rec.wall_total(), 6),
        'summaries': [tl.summary for tl in rec.scans()
                      if tl.summary is not None],
    })


@debug_route('/metrics',
             'Prometheus text exposition of the active registry.')
def _r_metrics(query):
    from . import device
    from .metrics import global_registry
    reg = device.registry() or global_registry()
    return (reg.render() if reg is not None else '',
            'text/plain; version=0.0.4', 200)


class ProfilingServer:
    """reference: pkg/profiling/pprof.go — starts only with -profile."""

    def __init__(self, port: int = 6060):
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 - quiet
                pass

            def _send(self, body: str, ctype='text/plain', code=200):
                data = body.encode()
                self.send_response(code)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                parsed = urlparse(self.path)
                path = parsed.path
                if path != '/' and path.endswith('/'):
                    path = path.rstrip('/')  # /debug/pprof/ == /debug/pprof
                if path in ('/debug', ''):
                    self._send(render_debug_index())
                    return
                route = _ROUTES.get(path)
                if route is None:
                    if path.startswith('/debug'):
                        # unknown debug path: 404 WITH the index, so a
                        # typo'd route answers with what exists
                        self._send('not found\n\n'
                                   + render_debug_index(), code=404)
                    else:
                        self._send('not found', code=404)
                    return
                try:
                    body, ctype, code = route.fn(parse_qs(parsed.query))
                except Exception as e:  # noqa: BLE001 - debug surface
                    self._send(f'internal error: {e}', code=500)
                    return
                self._send(body, ctype, code)

        self._httpd = ThreadingHTTPServer(('127.0.0.1', self.port),
                                          _Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name='ktpu-profiling', daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
