"""Decision provenance: per-decision attribution + the flight recorder.

PR 5 (micro-batching) and PR 6 (verdict cache) made individual
decisions invisible: one ``kyverno/serving/batch`` span serves up to 64
riders, cache replays never touch the device, and sheds land on the
host loop.  This module restores the per-request view — every admission
decision and every background-rescan row yields exactly one
:class:`DecisionRecord` naming the **serving path** that answered it:

* ``batch`` — rode a shared device dispatch (admission micro-batch or
  the rescan tick's dense scan); carries the batch id, its occupancy,
  and the **amortized device-time share** (batch ``device_eval`` stage
  time ÷ riders — shares of one batch sum to the batch's device time);
* ``sync`` — its own per-request device scan;
* ``shed:<reason>`` — left the batched fast path (reason from
  ``serving/shed.py``) and was served by the host engine loop;
* ``cache_replay`` — replayed from the digest-keyed verdict cache
  (carries the verdict digest, zero device share);
* ``host_fallback`` — the host engine loop served it directly (scanner
  still compiling, non-CREATE operation, exceptions present, device
  disabled, or a sync scan failure).

Records are exported three ways: (1) as attributes on the decision's
existing span, so the JSONL trace exporter carries them for free;
(2) through the bounded in-memory **flight recorder** ring (last
``KTPU_FLIGHT_N`` records, error/shed records kept in a second ring)
served at ``GET /debug/decisions`` and dumped to a JSONL file when the
d2h stall watchdog or a scan error fires; (3) on the cataloged
``kyverno_tpu_decision_duration_seconds{path}`` and
``kyverno_tpu_decision_device_share_seconds`` series.

Provenance never changes verdicts: records ride telemetry, not
``PolicyReport`` — everything here is a no-op until :func:`configure`
runs (and ``KTPU_FLIGHT_N=0`` keeps it off even then), with report and
admission output pinned bit-identical either way by
``tests/test_provenance.py``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import tracing
from .metrics import MetricsRegistry, global_registry

DECISION_DURATION = 'kyverno_tpu_decision_duration_seconds'
DECISION_DEVICE_SHARE = 'kyverno_tpu_decision_device_share_seconds'

#: decision latencies span sub-ms cache replays to multi-second
#: host-loop sweeps of 1k-policy sets
DURATION_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
#: amortized device shares live at (device_eval ÷ occupancy) — tens of
#: microseconds for a full batch up to ~1s for an unbatched cold scan
SHARE_BUCKETS = (0.00001, 0.00005, 0.0001, 0.00025, 0.0005, 0.001,
                 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0)

_DEFAULT_FLIGHT_N = 512

_batch_seq = itertools.count(1)


def _env_flight_n() -> int:
    try:
        return int(os.environ.get('KTPU_FLIGHT_N',
                                  str(_DEFAULT_FLIGHT_N)))
    except ValueError:
        return _DEFAULT_FLIGHT_N


def _env_dump_dir() -> Optional[str]:
    root = os.environ.get(
        'KTPU_FLIGHT_DUMP_DIR',
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), '.cache', 'flight'))
    return root or None


def next_batch_id(prefix: str = 'b') -> str:
    """Process-unique id for one shared dispatch (admission batch or
    rescan tick); riders of the same dispatch share it."""
    return f'{prefix}{next(_batch_seq)}'


def _engine_rev() -> str:
    from ..verdictcache.keys import engine_rev
    return engine_rev()  # memoized at the source


class DecisionRecord:
    """One decision's provenance.  Plain data: built once at decision
    completion, then only read (ring, endpoint, dump, span attrs)."""

    __slots__ = ('ts', 'trace_id', 'span_id', 'path', 'source', 'uid',
                 'kind', 'namespace', 'name', 'operation', 'duration_s',
                 'queue_wait_s', 'batch_id', 'occupancy',
                 'device_share_s', 'device_eval_s', 'aot_cache',
                 'coverage_ratio', 'fingerprint', 'engine_rev',
                 'verdict_digest', 'error')

    def __init__(self, ts: float, path: str, source: str, uid: str,
                 kind: str, namespace: str, name: str, operation: str,
                 duration_s: float, queue_wait_s: float, batch_id: str,
                 occupancy: int, device_share_s: float,
                 device_eval_s: float, aot_cache: str,
                 coverage_ratio: Optional[float], fingerprint: str,
                 engine_rev: str, verdict_digest: str, error: str,
                 trace_id: str = '', span_id: str = ''):
        self.ts = ts
        self.trace_id = trace_id
        self.span_id = span_id
        self.path = path
        self.source = source
        self.uid = uid
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.operation = operation
        self.duration_s = duration_s
        self.queue_wait_s = queue_wait_s
        self.batch_id = batch_id
        self.occupancy = occupancy
        self.device_share_s = device_share_s
        self.device_eval_s = device_eval_s
        self.aot_cache = aot_cache
        self.coverage_ratio = coverage_ratio
        self.fingerprint = fingerprint
        self.engine_rev = engine_rev
        self.verdict_digest = verdict_digest
        self.error = error

    @property
    def is_error(self) -> bool:
        return bool(self.error) or self.path.startswith('shed:')

    def to_dict(self) -> dict:
        out = {}
        for k in self.__slots__:
            v = getattr(self, k)
            if v in ('', None, 0, 0.0) and k not in ('ts', 'path',
                                                     'source'):
                continue  # compact: omit empty fields
            out[k] = round(v, 9) if isinstance(v, float) and k != 'ts' \
                else v
        return out


class FlightRecorder:
    """Bounded ring of the last N decision records, with error/shed
    records kept separately so a burst of healthy traffic cannot evict
    the interesting ones.  ``dump`` persists both rings as JSONL —
    fired automatically when the d2h stall watchdog or a scan error
    trips (rate-limited per trigger so a stall storm cannot fill the
    disk)."""

    DUMP_MIN_INTERVAL_S = 10.0

    def __init__(self, maxlen: int, dump_dir: Optional[str] = None,
                 now: Callable[[], float] = time.time):
        self.maxlen = maxlen
        self.dump_dir = dump_dir
        self.now = now
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=maxlen)
        self._errors: deque = deque(maxlen=maxlen)
        self._counts: Dict[str, int] = {}
        self._total = 0
        self._dump_seq = itertools.count(1)
        self._last_dump: Dict[str, float] = {}
        self.dump_paths: List[str] = []

    # -- writes ------------------------------------------------------------

    def record(self, rec: DecisionRecord) -> None:
        with self._lock:
            self._records.append(rec)
            if rec.is_error:
                self._errors.append(rec)
            self._counts[rec.path] = self._counts.get(rec.path, 0) + 1
            self._total += 1

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._errors.clear()
            self._counts.clear()
            self._total = 0

    # -- reads -------------------------------------------------------------

    def records(self, limit: Optional[int] = None) -> List[DecisionRecord]:
        with self._lock:
            out = list(self._records)
        return out[-limit:] if limit else out

    def errors(self, limit: Optional[int] = None) -> List[DecisionRecord]:
        with self._lock:
            out = list(self._errors)
        return out[-limit:] if limit else out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {'total': self._total, 'by_path': dict(self._counts),
                    'ring': len(self._records),
                    'error_ring': len(self._errors),
                    'capacity': self.maxlen}

    # -- dumps -------------------------------------------------------------

    def dump(self, trigger: str, force: bool = False) -> Optional[str]:
        """Write both rings to ``<dump_dir>/decisions-<trigger>-<n>.jsonl``
        (header line first).  Returns the path, or None when the dump
        directory is unset/unwritable or the trigger is rate-limited."""
        if self.dump_dir is None:
            return None
        now = self.now()
        with self._lock:
            last = self._last_dump.get(trigger, 0.0)
            if not force and now - last < self.DUMP_MIN_INTERVAL_S:
                return None
            self._last_dump[trigger] = now
            records = list(self._records)
            errors = list(self._errors)
        path = os.path.join(
            self.dump_dir,
            f'decisions-{trigger}-{os.getpid()}-{next(self._dump_seq)}'
            f'.jsonl')
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(path, 'w') as f:
                f.write(json.dumps({
                    'trigger': trigger, 'ts': now,
                    'records': len(records), 'errors': len(errors)})
                    + '\n')
                for rec in records:
                    f.write(json.dumps(
                        dict(rec.to_dict(), ring='decisions')) + '\n')
                for rec in errors:
                    f.write(json.dumps(
                        dict(rec.to_dict(), ring='errors')) + '\n')
        except OSError:
            return None
        self.dump_paths.append(path)
        return path


# -- module state -----------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_registry: Optional[MetricsRegistry] = None
_stall_sink: Optional[Callable[[dict], None]] = None


def configure(registry: Optional[MetricsRegistry] = None,
              flight_n: Optional[int] = None,
              dump_dir: Optional[str] = None,
              now: Callable[[], float] = time.time
              ) -> Optional[FlightRecorder]:
    """Enable decision provenance.  ``flight_n`` defaults to
    ``KTPU_FLIGHT_N`` (0 disables entirely — the off state the
    bit-identity tests pin against); ``dump_dir`` defaults to
    ``KTPU_FLIGHT_DUMP_DIR``.  Idempotent; :func:`disable` undoes it."""
    global _recorder, _registry, _stall_sink
    n = _env_flight_n() if flight_n is None else flight_n
    if n <= 0:
        disable()
        return None
    reg = registry or global_registry()
    if reg is not None:
        # bucket overrides must land before the first observe
        reg.register_histogram(DECISION_DURATION, DURATION_BUCKETS)
        reg.register_histogram(DECISION_DEVICE_SHARE, SHARE_BUCKETS)
    recorder = FlightRecorder(
        n, dump_dir if dump_dir is not None else _env_dump_dir(),
        now=now)
    if _stall_sink is None:
        # the d2h stall watchdog's structured event triggers a flight
        # dump: the ring's recent history lands on disk next to the
        # stall it explains
        def sink(event: dict) -> None:
            r = _recorder
            if r is not None:
                r.dump('d2h_stall')
        from . import device
        device.add_event_sink(sink)
        _stall_sink = sink
    _registry = reg
    _recorder = recorder
    return recorder


def disable() -> None:
    global _recorder, _registry, _stall_sink
    _recorder = None
    _registry = None
    if _stall_sink is not None:
        from . import device
        device.remove_event_sink(_stall_sink)
        _stall_sink = None


def recorder() -> Optional[FlightRecorder]:
    return _recorder


def enabled() -> bool:
    """The zero-overhead gate decision sites check (one global read)."""
    return _recorder is not None


def notify_scan_error(error: BaseException) -> None:
    """A device scan raised (sync or batched dispatch): dump the flight
    rings so the decisions leading up to the failure are on disk."""
    r = _recorder
    if r is not None:
        r.dump('scan_error')


def record_decision(path: str, source: str = 'admission', uid: str = '',
                    kind: str = '', namespace: str = '', name: str = '',
                    operation: str = '', duration_s: float = 0.0,
                    queue_wait_s: float = 0.0, batch_id: str = '',
                    occupancy: int = 0, device_share_s: float = 0.0,
                    device_eval_s: float = 0.0, aot_cache: str = '',
                    coverage_ratio: Optional[float] = None,
                    fingerprint: str = '', verdict_digest: str = '',
                    error: str = '') -> Optional[DecisionRecord]:
    """Build + publish one decision's record (no-op when provenance is
    unconfigured).  Stamps the ambient span (trace/span id into the
    record, the record's provenance fields onto the span so the JSONL
    exporter carries them) and the per-path decision metrics."""
    rec_sink = _recorder
    if rec_sink is None:
        return None
    span = tracing.current_span()
    trace_id = getattr(span, 'trace_id', '') if span is not None else ''
    span_id = getattr(span, 'span_id', '') if span is not None else ''
    rec = DecisionRecord(
        ts=rec_sink.now(), path=path, source=source, uid=uid, kind=kind,
        namespace=namespace, name=name, operation=operation,
        duration_s=duration_s, queue_wait_s=queue_wait_s,
        batch_id=batch_id, occupancy=occupancy,
        device_share_s=device_share_s, device_eval_s=device_eval_s,
        aot_cache=aot_cache, coverage_ratio=coverage_ratio,
        fingerprint=fingerprint, engine_rev=_engine_rev(),
        verdict_digest=verdict_digest, error=error,
        trace_id=trace_id, span_id=span_id)
    rec_sink.record(rec)
    if span is not None:
        span.set_attribute('decision_path', path)
        if batch_id:
            span.set_attribute('decision_batch_id', batch_id)
            span.set_attribute('decision_occupancy', occupancy)
        if device_share_s:
            span.set_attribute('decision_device_share_s',
                               round(device_share_s, 9))
        if verdict_digest:
            span.set_attribute('decision_verdict_digest', verdict_digest)
    reg = _registry or global_registry()
    if reg is not None:
        reg.observe(DECISION_DURATION, duration_s, path=path)
        if path in ('batch', 'sync'):
            reg.observe(DECISION_DEVICE_SHARE, device_share_s)
    return rec


# -- bench / endpoint views --------------------------------------------------

def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(len(sorted_vals) * q))]


def breakdown() -> Dict[str, Any]:
    """The ``decision_breakdown`` block ``bench.py`` embeds: per-path
    decision counts + p50/p95 latency, and the device-share histogram
    over batch/sync decisions — the homogeneous-vs-heterogeneous
    occupancy gap as a tracked number."""
    r = _recorder
    if r is None:
        return {}
    records = r.records()
    by_path: Dict[str, List[float]] = {}
    shares: List[float] = []
    for rec in records:
        by_path.setdefault(rec.path, []).append(rec.duration_s)
        if rec.path in ('batch', 'sync'):
            shares.append(rec.device_share_s)
    paths = {}
    stats = r.stats()
    for path, vals in sorted(by_path.items()):
        vals.sort()
        paths[path] = {
            'count': stats['by_path'].get(path, len(vals)),
            'p50_ms': round(_pctl(vals, 0.50) * 1000.0, 3),
            'p95_ms': round(_pctl(vals, 0.95) * 1000.0, 3),
        }
    share_hist: Dict[str, int] = {}
    for s in shares:
        for bound in SHARE_BUCKETS:
            if s <= bound:
                key = f'le_{bound}'
                share_hist[key] = share_hist.get(key, 0) + 1
                break
        else:
            share_hist['le_inf'] = share_hist.get('le_inf', 0) + 1
    shares.sort()
    return {
        'decisions': stats['total'],
        'paths': paths,
        'device_share': {
            'count': len(shares),
            'mean_s': round(sum(shares) / len(shares), 9)
            if shares else 0.0,
            'p50_s': round(_pctl(shares, 0.50), 9),
            'p95_s': round(_pctl(shares, 0.95), 9),
            'hist': share_hist,
        },
    }
