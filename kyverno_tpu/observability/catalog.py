"""Metric + span catalog: the single source of truth for every metric
name this process exports and every span name it starts.

Each series emitted through :class:`MetricsRegistry` (``inc`` /
``observe`` / ``set_gauge`` / ``clear_gauge``) must use a name listed
here with its type and help text; ``scripts/check_metric_names.py``
(run by ``tests/test_metric_catalog.py``) statically verifies every
call site against this table, so a typo'd or undocumented metric name
fails tier-1 instead of silently forking a series.  The ``SPANS``
table plays the same role for trace span names (KTPU504/505 in
ktpu-lint): a ``start_span`` site whose name is absent here — or a
cataloged span nothing starts — is catalog drift.
"""

from __future__ import annotations

from typing import Dict, NamedTuple


class Metric(NamedTuple):
    type: str  # counter | gauge | histogram
    help: str
    #: fleet identity axis for metrics emitted on the sharded mesh path
    #: (``kyverno_tpu/parallel/``): the label key every write site must
    #: carry so cross-host federation can tell series apart —
    #: ``'shard'`` (one series per mesh shard) or ``'mesh'`` (one
    #: series per mesh shape).  '' for single-host metrics.  Enforced
    #: by ktpu-lint KTPU509 (write sites under parallel/ must use a
    #: fleet-scoped metric and pass its label; a declared scope with no
    #: parallel/ write site is dead).
    fleet_scope: str = ''


METRICS: Dict[str, Metric] = {
    # engine / webhook instruments (reference: pkg/metrics/metrics.go)
    'kyverno_policy_results_total': Metric(
        'counter', 'Rule executions by policy/rule/result/resource.'),
    'kyverno_policy_execution_duration_seconds': Metric(
        'histogram', 'Per-policy engine execution latency.'),
    'kyverno_policy_changes_total': Metric(
        'counter', 'Policy create/update/delete events.'),
    'kyverno_policy_rule_info_total': Metric(
        'gauge', '1 per live (policy, rule) pair; retracted on delete.'),
    'kyverno_admission_review_duration_seconds': Metric(
        'histogram', 'End-to-end admission handler latency.'),
    'kyverno_admission_requests_total': Metric(
        'counter', 'Admission requests by operation/allowed.'),
    'kyverno_client_queries_total': Metric(
        'counter', 'Cluster client queries by verb/kind.'),
    # device-pipeline instruments (observability/device.py)
    'kyverno_tpu_scan_stage_duration_seconds': Metric(
        'histogram', 'Batched-scan stage latency; stage=pack|encode|h2d|'
        'compile|device_eval|d2h|report.'),
    'kyverno_tpu_compile_cache_requests_total': Metric(
        'counter', 'Evaluator executable lookups; result=hit|miss|'
        'aot_load|aot_store.'),
    'kyverno_tpu_device_batch_size': Metric(
        'gauge', 'Rows in the most recent device chunk.'),
    'kyverno_tpu_d2h_bytes_total': Metric(
        'counter', 'Device-to-host readback bytes.'),
    'kyverno_tpu_d2h_stalls_total': Metric(
        'counter', 'Readbacks exceeding the stall watchdog threshold '
        '(KTPU_D2H_STALL_S, default 30s).'),
    'kyverno_tpu_scan_pipeline_inflight_chunks': Metric(
        'gauge', 'Chunks resident in the streaming scan pipeline '
        '(bounded by KTPU_PIPELINE_DEPTH; intake backpressures at the '
        'bound instead of buffering).'),
    'kyverno_tpu_scan_backpressure_seconds_total': Metric(
        'counter', 'Time a scan-pipeline stage spent blocked on a full '
        'downstream queue (stage=intake|encode|h2d|device_eval|d2h) — '
        'which leg bounds the stream.'),
    # device-coverage ledger (observability/coverage.py)
    'kyverno_tpu_rule_placement_info': Metric(
        'gauge', '1 per compiled (policy, rule, path); placement=device|'
        'host|partial with the fallback-reason taxonomy slug.'),
    'kyverno_tpu_host_fallback_total': Metric(
        'counter', 'Rows served by the host engine instead of the '
        'device/fast path, by path=validate|mutate|pss and attributed '
        'reason (observability/coverage.py REASONS).'),
    'kyverno_tpu_device_coverage_ratio': Metric(
        'gauge', 'Device-decided fraction of the most recent scan '
        '(device_rows / total_rows).'),
    # admission micro-batching scheduler (serving/)
    'kyverno_tpu_admission_queue_depth': Metric(
        'gauge', 'Pending requests in the admission micro-batch queue '
        '(KTPU_QUEUE_CAP bounds it; overflow sheds to the host loop).'),
    'kyverno_tpu_admission_batch_occupancy': Metric(
        'histogram', 'Coalesced requests per shared device dispatch '
        '(flushes on the KTPU_BATCH_WINDOW_MS window or at '
        'KTPU_BATCH_MAX occupancy).'),
    'kyverno_tpu_admission_hetero_occupancy': Metric(
        'histogram', 'Coalesced requests per shared dispatch whose '
        'riders carried MORE than one distinct canonical admission '
        'tuple (heterogeneous traffic) — distinguishes real mixed-user '
        'coalescing from same-tuple batching in production telemetry.'),
    'kyverno_tpu_admission_queue_wait_seconds': Metric(
        'histogram', 'Time a request waited in the admission queue '
        'before its batch dispatched.'),
    'kyverno_tpu_admission_shed_total': Metric(
        'counter', 'Requests shed from the batched fast path to the '
        'host engine loop, by reason=queue_full|deadline|scan_error|'
        'shutdown|poison_row|breaker_open|stage_retry_exhausted '
        '(never a 500).'),
    # degradation under failure (faults/, serving/breaker.py)
    'kyverno_tpu_faults_injected_total': Metric(
        'counter', 'Faults the KTPU_FAULTS injection harness actually '
        'raised, by site= (chaos drills only; zero in production).'),
    'kyverno_tpu_breaker_state': Metric(
        'gauge', 'Per-policy-set circuit breakers in each lifecycle '
        'state, by state=closed|open|half_open (serving/breaker.py).'),
    'kyverno_tpu_breaker_evictions_total': Metric(
        'counter', 'Breaker entries evicted by the KTPU_BREAKER_CAP '
        'bound; forgetting breaker state can silently re-admit a '
        'broken backend, so evictions are counted, never silent.'),
    # verdict cache + incremental rescans (verdictcache/)
    'kyverno_tpu_verdict_cache_hits_total': Metric(
        'counter', 'Background-rescan rows replayed from the '
        'digest-keyed verdict cache instead of re-scanning.'),
    'kyverno_tpu_verdict_cache_misses_total': Metric(
        'counter', 'Verdict-cache lookups that missed (changed or '
        'never-seen spec digest) and shipped to the dense scan.'),
    'kyverno_tpu_verdict_cache_evictions_total': Metric(
        'counter', 'Verdict rows dropped by the memory-LRU entry cap '
        'or generation snapshots dropped by the disk byte budget '
        '(KTPU_VERDICT_CACHE_MAX).'),
    'kyverno_tpu_verdict_cache_partial_hits_total': Metric(
        'counter', 'Partitioned-cache lookups that missed the full row '
        'but held every unchanged partition\'s subrow — the row '
        're-scanned against only the touched partitions\' policies '
        '(verdictcache/partitioned.py).'),
    'kyverno_tpu_rescan_rows_scanned': Metric(
        'gauge', 'Rows the most recent background reconcile evaluated '
        'on the dense device path.'),
    'kyverno_tpu_rescan_rows_replayed': Metric(
        'gauge', 'Rows the most recent background reconcile replayed '
        'from the verdict cache.'),
    # partitioned policy-set compilation (kyverno_tpu/partition/)
    'kyverno_tpu_partition_count': Metric(
        'gauge', 'Device-evaluated partitions of the most recently '
        'built partitioned scanner (KTPU_PARTITIONS).'),
    'kyverno_tpu_partition_recompiles_total': Metric(
        'counter', 'Partition evaluators built fresh (no evaluator-'
        'cache entry for the partition fingerprint) — under policy '
        'churn this should track touched partitions, not the set.'),
    'kyverno_tpu_partition_evaluator_reuses_total': Metric(
        'counter', 'Partition evaluators served from the process-wide '
        'evaluator cache (fingerprint unchanged across a scanner '
        'rebuild).'),
    'kyverno_tpu_partition_fallbacks_total': Metric(
        'counter', 'Scanner builds that requested partitioning but '
        'fell back to the monolithic whole-set compile '
        '(PartitionError: unsupported layout for composition).'),
    # scanner hot-swap under live traffic (webhooks/handlers.py)
    'kyverno_tpu_scanner_hot_swaps_total': Metric(
        'counter', 'Live scanner replacements after policy churn: the '
        'successor took over a same-kind predecessor\'s slot without '
        'draining traffic, by kind=.'),
    'kyverno_tpu_breaker_migrations_total': Metric(
        'counter', 'Circuit-breaker entries carried from a retired '
        'scanner\'s key to its hot-swap successor instead of being '
        'reset to closed.'),
    # AOT cache + warm-up instruments (aotcache/)
    'kyverno_tpu_aot_warm_duration_seconds': Metric(
        'histogram', 'Background warm-up wall time by target/state '
        '(aotcache/warmer.py).'),
    'kyverno_tpu_aot_cache_size_bytes': Metric(
        'gauge', 'Bytes of persisted AOT executables on disk '
        '(KTPU_AOT_CACHE_DIR).'),
    'kyverno_tpu_aot_cache_entries': Metric(
        'gauge', 'Persisted AOT executable entries on disk.'),
    'kyverno_tpu_aot_load_rejected_total': Metric(
        'counter', 'AOT store entries dropped instead of loaded; '
        'reason=undecodable|feature_mismatch|env_mismatch|jax_mismatch|'
        'deserialize_failed|execute_failed (a rejected entry falls back '
        'to a fresh persistent-XLA-cache-assisted compile, never a '
        'possibly-SIGILL load).'),
    # device-side mutate (kyverno_tpu/mutate/scanner.py)
    'kyverno_tpu_mutate_patch_emit_seconds': Metric(
        'histogram', 'Mutate patch-emit stage: encode the edit-site '
        'lanes and run the device kernel that decides per-(resource, '
        'rule) edit bitmasks.'),
    'kyverno_tpu_mutate_decode_seconds': Metric(
        'histogram', 'Mutate decode stage: edit bitmasks back to '
        '(slot, value) edit lists, copy-on-write patch application, '
        'and EngineResponse assembly on the host.'),
    'kyverno_tpu_mutate_device_edits_total': Metric(
        'counter', 'Individual edits applied from device-decided '
        'mutate edit lists.'),
    # decision provenance (observability/provenance.py)
    'kyverno_tpu_decision_duration_seconds': Metric(
        'histogram', 'End-to-end per-decision latency by serving '
        'path=batch|sync|shed:<reason>|cache_replay|host_fallback.'),
    'kyverno_tpu_decision_device_share_seconds': Metric(
        'histogram', 'Amortized device time one decision consumed '
        '(its batch device_eval time / riders; sync decisions carry '
        'their whole scan).'),
    # tracing health (observability/tracing.py)
    'kyverno_tpu_trace_export_errors_total': Metric(
        'counter', 'Span-exporter failures by exporter class; an '
        'exporter failing repeatedly is dropped after this counts it, '
        'so a dead exporter is visible instead of silent.'),
    # executable ledger (observability/executables.py)
    'kyverno_tpu_executable_count': Metric(
        'gauge', 'Live compiled executables in the lifecycle ledger, '
        'by source=fresh_compile|aot_load|persistent_xla.'),
    'kyverno_tpu_executable_dispatches_total': Metric(
        'counter', 'Device dispatches served per executable '
        'acquisition source.'),
    'kyverno_tpu_executable_device_seconds_total': Metric(
        'counter', 'Cumulative device-eval seconds spent per '
        'executable acquisition source.'),
    # pipeline critical-path observatory (observability/timeline.py)
    'kyverno_tpu_pipeline_blame_seconds_total': Metric(
        'counter', 'Exclusive critical-path blame per streaming-scan '
        'stage: seconds of scan wall the timeline walk attributed to '
        'stage= (executing or gated-waiting while on the e2e critical '
        'path); per-scan fractions drive the bottleneck advisor.'),
    # mesh-step telemetry (parallel/mesh.py, observability/fleet.py)
    'kyverno_tpu_mesh_step_duration_seconds': Metric(
        'histogram', 'Sharded-dispatch wall per mesh step: one series '
        'per shard index with that shard\'s device-eval wait '
        '(host-side block_until_ready split, arrival order), plus '
        'shard=all for the whole step.', fleet_scope='shard'),
    'kyverno_tpu_mesh_shard_skew_ratio': Metric(
        'gauge', 'Max-shard / mean-shard device-eval wall of the most '
        'recent mesh step, per mesh shape — 1.0 is a perfectly '
        'balanced step; the fleet skew analyzer windows this '
        '(KTPU_FLEET_SKEW_WINDOW) to name stragglers.',
        fleet_scope='mesh'),
    'kyverno_tpu_mesh_collective_seconds_total': Metric(
        'counter', 'Cumulative wall spent in cross-shard collectives '
        '(psum\'d summary readback + multi-host allgather) per mesh '
        'shape.', fleet_scope='mesh'),
    'kyverno_tpu_mesh_padding_rows_total': Metric(
        'counter', 'Rows added to pad mesh batches up to a multiple '
        'of the mesh size (canonical capacity included) — wasted '
        'device work per mesh shape.', fleet_scope='mesh'),
    # registry self-protection (observability/metrics.py)
    'kyverno_tpu_metric_series_dropped_total': Metric(
        'counter', 'New label-sets refused because a metric already '
        'held KTPU_METRIC_SERIES_MAX distinct series, by metric= — '
        'per-host/per-shard labels cannot explode the registry under '
        'a large fleet.'),
    # serving SLO engine (observability/slo.py)
    'kyverno_tpu_slo_burn_rate': Metric(
        'gauge', 'Admission-latency error-budget burn rate '
        '(error_rate / (1 - KTPU_SLO_TARGET)) by window=short|long; '
        '1.0 spends the budget exactly at the sustainable rate.'),
    'kyverno_tpu_slo_budget_remaining': Metric(
        'gauge', 'Fraction of the long-window error budget left '
        '(1 - long-window burn rate); negative means overspent.'),
}


#: every span name this process starts, with what it covers — the
#: tracing analogue of METRICS, drift-checked by ktpu-lint KTPU504/505.
#: ``<...>`` segments mark route-/name-templated spans whose start
#: sites build the name dynamically (an f-string site is checked by its
#: literal prefix).
SPANS: Dict[str, str] = {
    'webhooks/<route>': 'Admission HTTP handler root span (one per '
                        'request; route-templated).',
    'kyverno/engine/rule': 'One host-engine rule execution.',
    'kyverno/serving/batch': 'One coalesced admission dispatch (batch '
                             'serving mode); carries occupancy.',
    'kyverno/device/scan': 'One device-scan chunk: device wait + host '
                           'assembly.',
    'kyverno/device/chunk': 'Dispatch-thread wrapper seeding the '
                            'per-chunk stage spans.',
    'kyverno/device/pack': 'Pack-plan build stage.',
    'kyverno/device/encode': 'Host feature-extraction (encode) stage.',
    'kyverno/device/h2d': 'Host-to-device transfer stage.',
    'kyverno/device/compile': 'Executable lookup / XLA compile stage.',
    'kyverno/device/device_eval': 'Device evaluation dispatch stage.',
    'kyverno/device/d2h': 'Device-to-host readback stage (stall-'
                          'watchdog armed).',
    'kyverno/device/report': 'Response/report assembly stage.',
    'kyverno/mutate/patch_emit': 'Device mutate patch-emit stage: '
                                 'edit-site lane encode + kernel '
                                 'dispatch for one batch.',
    'kyverno/mutate/decode': 'Device mutate decode stage: edit '
                             'bitmasks to patched JSON + engine '
                             'responses.',
    'kyverno/mesh/step': 'One sharded mesh dispatch '
                         '(distributed_scan_step): carries mesh '
                         'shape, per-shard row occupancy, skew ratio '
                         'and the blamed straggler shard.',
    'kyverno/rescan': 'One background reconcile tick (verdict-cache '
                      'filter + dense scan of the misses).',
    'kyverno/background/ur': 'One UpdateRequest sync.',
    'kyverno/aot/warmer': 'Background AOT warm-up pass.',
    'kyverno/executable/<event>': 'Executable-ledger lifecycle event '
                                  '(build/evict) as a zero-duration '
                                  'span; the JSONL trace exporter is '
                                  'the lifecycle log.',
}


#: canonical streaming-pipeline stage labels — the single source of
#: truth for every ``stage('<s>')`` timer, ``ChunkPipeline`` stage-list
#: entry, and backpressure attribution in the tree (ktpu-lint KTPU507:
#: an unregistered label under ``compiler/`` or a dead registry entry
#: is catalog drift).  The timeline recorder and its critical-path
#: blame walk (observability/timeline.py) group events by these names.
PIPELINE_STAGES: Dict[str, str] = {
    'intake': 'Feeder admission into the streaming pipeline (chunk '
              'slot acquire + first-queue handoff).',
    'pack': 'Pack-plan build.',
    'encode': 'Host feature extraction (columnar lane encode, inline '
              'or forked worker).',
    'h2d': 'Host-to-device transfer (and forked-encode resolution).',
    'compile': 'Executable lookup / XLA compile.',
    'device_eval': 'Device evaluation dispatch.',
    'd2h': 'Device-to-host readback (stall-watchdog armed).',
    'report': 'Report-row assembly / flush window.',
}
