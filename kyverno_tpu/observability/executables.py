"""Executable ledger: a lifecycle record for every compiled program.

The ROADMAP's next pushes (incremental policy-set compilation,
multi-host scale-out) both hinge on "which executables exist, what did
each cost to build, and who is spending device time on what" — yet
executables have been anonymous entries in the AOT store.  This module
registers an :class:`ExecutableRecord` at every acquisition site in
``ops/eval.py``:

* ``fresh_compile`` — a ``jitted.lower(packed).compile()`` miss (the
  warm-up wall, measured per executable);
* ``aot_load`` — deserialized from the AOT disk store
  (``compiler/aot.py``);
* ``persistent_xla`` — the jit-fallback path (mesh-sharded inputs or
  AOT disabled) whose first call compiles through ``jax.jit`` backed by
  the persistent XLA compilation cache.

Each record carries the policy-set fingerprint, the canonical row
capacity, build/load duration, ``compiled.cost_analysis()`` flops and
bytes where the backend reports them, cumulative dispatch count +
device-eval seconds, and the last-used timestamp.  Evictions
(``execute_failed`` artifacts dropped by ``_evict_aot``) mark the
record instead of silently removing it.

Exports: ``kyverno_tpu_executable_count{source}`` (live records),
``kyverno_tpu_executable_dispatches_total{source}`` and
``kyverno_tpu_executable_device_seconds_total{source}``; the full table
serves at ``GET /debug/executables`` (JSON, ``?format=table`` for a
terminal view); build/evict lifecycle events ride the existing tracer
exporters as zero-duration ``kyverno/executable/<event>`` spans, so a
``tracing.configure(jsonl_path=...)`` run leaves a JSONL lifecycle log
for free.

Same no-op contract as the rest of telemetry: nothing is recorded until
:func:`configure` runs (``KTPU_EXEC_LEDGER_N=0`` keeps it off), and the
ledger rides telemetry, never the scan output — bit-identity on/off is
pinned by ``tests/test_executables.py``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from . import tracing
from .metrics import MetricsRegistry, global_registry

EXEC_COUNT = 'kyverno_tpu_executable_count'
EXEC_DISPATCHES = 'kyverno_tpu_executable_dispatches_total'
EXEC_DEVICE_SECONDS = 'kyverno_tpu_executable_device_seconds_total'

#: executable acquisition sources, in "how much did it cost" order
SOURCES = ('fresh_compile', 'aot_load', 'persistent_xla')

_DEFAULT_LEDGER_N = 256


def _env_ledger_n() -> int:
    try:
        return int(os.environ.get('KTPU_EXEC_LEDGER_N',
                                  str(_DEFAULT_LEDGER_N)))
    except ValueError:
        return _DEFAULT_LEDGER_N


def cost_analysis(compiled) -> Dict[str, float]:
    """(flops, bytes accessed) from ``compiled.cost_analysis()`` where
    the backend reports them; {} when unavailable (older jax returns a
    per-device list, some backends return nothing)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - diagnostics only
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    for field, key in (('flops', 'flops'),
                       ('bytes_accessed', 'bytes accessed')):
        try:
            v = float(ca.get(key, 0.0) or 0.0)
        except (TypeError, ValueError):
            continue
        if v > 0:
            out[field] = v
    return out


class ExecutableRecord:
    """One compiled program's lifecycle.  Mutated only under the
    ledger's lock (dispatch accounting, eviction marking)."""

    __slots__ = ('key', 'fingerprint', 'capacity', 'source', 'build_s',
                 'flops', 'bytes_accessed', 'dispatches', 'device_s',
                 'created_ts', 'last_used_ts', 'evicted', 'evict_reason')

    def __init__(self, key: str, fingerprint: str, capacity: int,
                 source: str, build_s: float, flops: float,
                 bytes_accessed: float, ts: float):
        self.key = key
        self.fingerprint = fingerprint
        self.capacity = capacity
        self.source = source
        self.build_s = build_s
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.dispatches = 0
        self.device_s = 0.0
        self.created_ts = ts
        self.last_used_ts = ts
        self.evicted = False
        self.evict_reason = ''

    def to_dict(self) -> dict:
        out: Dict[str, Any] = {
            'key': self.key[:16],
            'fingerprint': self.fingerprint[:16],
            'capacity': self.capacity,
            'source': self.source,
            'build_s': round(self.build_s, 6),
            'dispatches': self.dispatches,
            'device_s': round(self.device_s, 6),
            'created_ts': self.created_ts,
            'last_used_ts': self.last_used_ts,
        }
        if self.flops:
            out['flops'] = self.flops
        if self.bytes_accessed:
            out['bytes_accessed'] = self.bytes_accessed
        if self.evicted:
            out['evicted'] = True
            out['evict_reason'] = self.evict_reason
        return out


class ExecutableLedger:
    """Bounded registry of executable records, keyed by the AOT cache
    key (or the jit-signature pseudo-key on the fallback path).  Over
    the bound, the least-recently-used record is dropped — a churn-heavy
    future (incremental recompiles) cannot grow it without bound."""

    def __init__(self, maxlen: int,
                 registry: Optional[MetricsRegistry] = None,
                 now: Callable[[], float] = time.time):
        self.maxlen = maxlen
        self.registry = registry
        self.now = now
        self._lock = threading.Lock()
        self._records: 'OrderedDict[str, ExecutableRecord]' = OrderedDict()

    # -- writes ------------------------------------------------------------

    def record_build(self, key: str, fingerprint: str = '',
                     capacity: int = 0, source: str = 'fresh_compile',
                     build_s: float = 0.0,
                     compiled: Any = None) -> ExecutableRecord:
        costs = cost_analysis(compiled) if compiled is not None else {}
        with self._lock:
            rec = self._records.pop(key, None)
            if rec is not None and not rec.evicted:
                # re-acquisition of a known key (e.g. recompile after an
                # eviction raced): refresh source + build cost, keep the
                # cumulative dispatch history
                rec.source = source
                rec.build_s = build_s
                rec.last_used_ts = self.now()
            else:
                rec = ExecutableRecord(
                    key=key, fingerprint=fingerprint, capacity=capacity,
                    source=source, build_s=build_s,
                    flops=costs.get('flops', 0.0),
                    bytes_accessed=costs.get('bytes_accessed', 0.0),
                    ts=self.now())
            self._records[key] = rec
            while len(self._records) > self.maxlen:
                self._records.popitem(last=False)
            self._set_count_gauges()
        self._lifecycle_event('build', rec)
        return rec

    def record_dispatch(self, key: str, device_s: float) -> None:
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return
            rec.dispatches += 1
            rec.device_s += device_s
            rec.last_used_ts = self.now()
            self._records.move_to_end(key)
            source = rec.source
        reg = self.registry or global_registry()
        if reg is not None:
            reg.inc(EXEC_DISPATCHES, source=source)
            reg.inc(EXEC_DEVICE_SECONDS, float(device_s), source=source)

    def record_eviction(self, key: str, reason: str) -> None:
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return
            rec.evicted = True
            rec.evict_reason = reason
            self._set_count_gauges()
        self._lifecycle_event('evict', rec)

    # -- metric + lifecycle plumbing ---------------------------------------

    def _set_count_gauges(self) -> None:
        """Live (non-evicted) record count per source — called under
        the lock after every membership change so the gauge tracks the
        ledger exactly."""
        reg = self.registry or global_registry()
        if reg is None:
            return
        counts = {s: 0 for s in SOURCES}
        for rec in self._records.values():
            if not rec.evicted:
                counts[rec.source] = counts.get(rec.source, 0) + 1
        # the ledger's live record count is residency — a shut-down
        # process holds no executables, so the series must drain to 0
        reg.mark_reset_on_close(EXEC_COUNT)
        for source, n in counts.items():
            reg.set_gauge(EXEC_COUNT, float(n), source=source)

    def _lifecycle_event(self, event: str, rec: ExecutableRecord) -> None:
        """Build/evict event as a zero-duration span: the existing
        tracer exporters (memory ring, JSONL file) carry the executable
        lifecycle log with no new export machinery."""
        tr = tracing.tracer()
        if not tr.enabled:
            return
        attrs: Dict[str, Any] = {
            'key': rec.key[:16], 'fingerprint': rec.fingerprint[:16],
            'capacity': rec.capacity, 'source': rec.source,
            'build_s': round(rec.build_s, 6),
        }
        if event == 'evict':
            attrs['evict_reason'] = rec.evict_reason
            attrs['dispatches'] = rec.dispatches
            attrs['device_s'] = round(rec.device_s, 6)
        tr.start_span(f'kyverno/executable/{event}', attrs,
                      parent=tracing.current_span()).end()

    # -- reads -------------------------------------------------------------

    def records(self) -> List[ExecutableRecord]:
        with self._lock:
            return list(self._records.values())

    def census(self) -> Dict[str, Any]:
        """The compact summary bench.py embeds: live counts by source +
        cumulative dispatch/device totals."""
        with self._lock:
            recs = list(self._records.values())
        by_source: Dict[str, int] = {}
        dispatches = 0
        device_s = 0.0
        build_s = 0.0
        for rec in recs:
            dispatches += rec.dispatches
            device_s += rec.device_s
            if not rec.evicted:
                by_source[rec.source] = by_source.get(rec.source, 0) + 1
                build_s += rec.build_s
        return {
            'live': sum(by_source.values()),
            'by_source': by_source,
            'dispatches': dispatches,
            'device_s': round(device_s, 6),
            'build_s': round(build_s, 6),
        }

    def report(self) -> Dict[str, Any]:
        """The ``/debug/executables`` JSON body."""
        return {
            'enabled': True,
            'capacity': self.maxlen,
            'census': self.census(),
            'executables': [rec.to_dict() for rec in self.records()],
        }

    def render_table(self) -> str:
        """Terminal view of the ledger (``?format=table``)."""
        header = (f'{"KEY":<18}{"FPRINT":<18}{"CAP":>6}  '
                  f'{"SOURCE":<14}{"BUILD_S":>10}{"DISP":>8}'
                  f'{"DEVICE_S":>11}  STATE')
        lines = [header, '-' * len(header)]
        for rec in self.records():
            state = f'evicted:{rec.evict_reason}' if rec.evicted \
                else 'live'
            lines.append(
                f'{rec.key[:16]:<18}{rec.fingerprint[:16]:<18}'
                f'{rec.capacity:>6}  {rec.source:<14}'
                f'{rec.build_s:>10.3f}{rec.dispatches:>8}'
                f'{rec.device_s:>11.4f}  {state}')
        if len(lines) == 2:
            lines.append('(no executables registered)')
        return '\n'.join(lines) + '\n'


# -- module state -----------------------------------------------------------

_ledger: Optional[ExecutableLedger] = None


def configure(registry: Optional[MetricsRegistry] = None,
              ledger_n: Optional[int] = None,
              now: Callable[[], float] = time.time
              ) -> Optional[ExecutableLedger]:
    """Enable the executable ledger.  ``ledger_n`` defaults to
    ``KTPU_EXEC_LEDGER_N`` (0 disables entirely — the off state the
    bit-identity tests pin against).  Idempotent; :func:`disable`
    undoes it."""
    global _ledger
    n = _env_ledger_n() if ledger_n is None else ledger_n
    if n <= 0:
        disable()
        return None
    _ledger = ExecutableLedger(n, registry or global_registry(), now=now)
    return _ledger


def disable() -> None:
    global _ledger
    _ledger = None


def ledger() -> Optional[ExecutableLedger]:
    return _ledger


def enabled() -> bool:
    """The zero-overhead gate the compile/dispatch sites check (one
    global read)."""
    return _ledger is not None


# -- registration hooks (called from ops/eval.py + compiler/aot.py) ---------

def record_build(key: str, fingerprint: str = '', capacity: int = 0,
                 source: str = 'fresh_compile', build_s: float = 0.0,
                 compiled: Any = None) -> None:
    led = _ledger
    if led is not None:
        led.record_build(key, fingerprint=fingerprint,
                         capacity=capacity, source=source,
                         build_s=build_s, compiled=compiled)


def record_dispatch(key: str, device_s: float) -> None:
    led = _ledger
    if led is not None:
        led.record_dispatch(key, device_s)


def record_eviction(key: str, reason: str) -> None:
    led = _ledger
    if led is not None:
        led.record_eviction(key, reason)


def census() -> Dict[str, Any]:
    """Bench view (empty when unconfigured)."""
    led = _ledger
    return led.census() if led is not None else {}
