"""Pipeline critical-path observatory: per-chunk stage timelines.

The streaming scan pipeline (``compiler/pipeline.py``) overlaps encode,
h2d, device_eval, d2h and report assembly across worker threads; the
coarse per-stage busy/wall ratios already exported cannot say which
stage actually bounds the end-to-end wall — a stage can be 90% busy and
still be entirely off the critical path.  This module records a bounded,
lock-light per-chunk event timeline (enqueue, exec, retry and
backpressure-block intervals with thread identity) and walks the chunk
DAG backwards from the last event to attribute every second of scan
wall to exactly one stage as exclusive "blame":

* a chunk×stage node is gated by its upstream stage on the same chunk
  and by the same stage on the previous chunk (one worker per stage,
  FIFO) — whichever ended last is the edge the critical path follows;
* the segment between the gate's end and the node's end is blamed on
  the node's stage, split into ``executing`` (the stage was running)
  and ``waiting`` (queued / blocked while on the path);
* the walk terminates at the scan origin, so blame seconds sum exactly
  to the scan wall — fractions are directly "what to speed up".

Everything is off until :func:`configure` runs, and ``KTPU_TIMELINE=0``
keeps it off entirely — the scan path is bit-identical to a build
without this module (the same contract as the flight recorder and the
admission SLO engine).  When on, the per-scan event budget is bounded
by ``KTPU_TIMELINE_N``; events past it are counted, never buffered.
"""

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import device as devtel

#: counter: exclusive critical-path seconds attributed per stage=
PIPELINE_BLAME = 'kyverno_tpu_pipeline_blame_seconds_total'

#: dataflow order of the chunk DAG — the blame walk only follows these
#: stages; auxiliary labels ('intake' feeder accounting, watchdog
#: spans) still land in the trace but never on the critical path.
STAGE_ORDER = ('pack', 'encode', 'h2d', 'compile', 'device_eval',
               'd2h', 'report')

_ORDER_IDX = {s: i for i, s in enumerate(STAGE_ORDER)}

EVENT_KINDS = ('exec', 'queue', 'retry', 'block')


class StageEvent:
    """One closed interval on a chunk's lifeline.

    ``kind`` is one of ``exec`` (the stage ran), ``queue`` (sitting in
    the inter-stage queue), ``retry`` (backoff sleep before re-running
    the stage) or ``block`` (producer blocked pushing downstream /
    feeder blocked on the depth semaphore).
    """

    __slots__ = ('chunk', 'stage', 'kind', 't0', 't1', 'thread',
                 'attempt')

    def __init__(self, chunk, stage, kind, t0, t1, thread='', attempt=0):
        self.chunk = chunk
        self.stage = stage
        self.kind = kind
        self.t0 = t0
        self.t1 = t1
        self.thread = thread
        self.attempt = attempt


class ScanTimeline:
    """Event log for one scan: append-only, bounded, lock-light.

    Pipeline worker threads touch disjoint ``(chunk, stage)`` keys and
    CPython list-append / dict set-pop are atomic, so the hot-path
    methods take no lock; only finalization (single-threaded, after the
    workers joined) aggregates.
    """

    __slots__ = ('scan_id', 't0', 't_end', 'max_events', 'events',
                 'dropped', '_open', '_pending', 'summary')

    def __init__(self, scan_id: int, max_events: int):
        self.scan_id = scan_id
        self.t0 = time.monotonic()
        self.t_end: Optional[float] = None
        self.max_events = max_events
        self.events: List[StageEvent] = []
        self.dropped = 0
        self._open: Dict[Tuple[int, str], Tuple[float, str]] = {}
        self._pending: Dict[Tuple[int, str], float] = {}
        self.summary: Optional[Dict[str, Any]] = None

    # -- hot path ---------------------------------------------------------

    def _add(self, ev: StageEvent) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(ev)

    def enqueue(self, chunk: int, stage: str) -> None:
        """Mark the chunk as handed to ``stage``'s input queue."""
        self._pending[(chunk, stage)] = time.monotonic()

    def start(self, chunk: int, stage: str) -> None:
        """The stage's worker picked the chunk up and began executing."""
        now = time.monotonic()
        key = (chunk, stage)
        t_q = self._pending.pop(key, None)
        if t_q is not None and now > t_q:
            self._add(StageEvent(chunk, stage, 'queue', t_q, now,
                                 threading.current_thread().name))
        self._open[key] = (now, threading.current_thread().name)

    def end(self, chunk: int, stage: str, ok: bool = True) -> None:
        """The stage finished (or errored out) on this chunk."""
        now = time.monotonic()
        entry = self._open.pop((chunk, stage), None)
        if entry is None:
            return
        t_start, thread = entry
        self._add(StageEvent(chunk, stage, 'exec', t_start, now, thread,
                             attempt=0 if ok else -1))

    def record(self, stage: str, chunk: int, t0: float,
               t1: Optional[float] = None, kind: str = 'exec',
               thread: Optional[str] = None, attempt: int = 0) -> None:
        """Record an already-measured interval (inline paths, forked
        encode workers shipping their timing home, report windows)."""
        self._add(StageEvent(
            chunk, stage, kind, t0,
            time.monotonic() if t1 is None else t1,
            threading.current_thread().name if thread is None else thread,
            attempt))

    def retry(self, chunk: int, stage: str, t0: float,
              attempt: int) -> None:
        self.record(stage, chunk, t0, kind='retry', attempt=attempt)

    def block(self, chunk: int, stage: str, t0: float) -> None:
        self.record(stage, chunk, t0, kind='block')

    # -- finalization -----------------------------------------------------

    def open_count(self) -> int:
        """Exec intervals started but never ended (must be 0 after a
        pipeline drain, including early generator close)."""
        return len(self._open)

    def close_open(self) -> None:
        """Close any still-open exec intervals (pipeline teardown path:
        a stage aborted mid-chunk on early generator close)."""
        now = time.monotonic()
        for key in list(self._open):
            entry = self._open.pop(key, None)
            if entry is None:
                continue
            t_start, thread = entry
            self._add(StageEvent(key[0], key[1], 'exec', t_start, now,
                                 thread, attempt=-1))
        self._pending.clear()

    def finalize(self) -> Dict[str, Any]:
        if self.summary is not None:
            return self.summary
        self.close_open()
        self.t_end = time.monotonic()
        self.summary = analyze(self.events, self.t0, self.t_end)
        self.summary['scan_id'] = self.scan_id
        self.summary['events'] = len(self.events)
        self.summary['dropped'] = self.dropped
        return self.summary


# -- critical-path analysis ---------------------------------------------------


def analyze(events: Iterable[StageEvent], t0: float,
            t_end: float) -> Dict[str, Any]:
    """Walk the chunk DAG backwards and attribute wall time to stages.

    Merges exec events per (chunk, stage) node, then from the
    latest-ending node repeatedly blames the segment back to its gating
    predecessor's end — the predecessor being whichever of (same chunk,
    nearest upstream stage) / (previous chunk, same stage) ended last.
    The walk bottoms out at the scan origin and a trailing consumer
    segment is charged to report, so blame sums exactly to the wall.
    """
    wall = max(0.0, t_end - t0)
    execs: Dict[Tuple[int, str], List[float]] = {}
    for ev in events:
        if ev.kind != 'exec' or ev.stage not in _ORDER_IDX:
            continue
        key = (ev.chunk, ev.stage)
        cur = execs.get(key)
        if cur is None:
            execs[key] = [ev.t0, ev.t1, ev.t1 - ev.t0]
        else:
            cur[0] = min(cur[0], ev.t0)
            cur[1] = max(cur[1], ev.t1)
            cur[2] += ev.t1 - ev.t0

    blame: Dict[str, float] = {}
    executing: Dict[str, float] = {}
    waiting: Dict[str, float] = {}

    def charge(stage, seg, ex):
        blame[stage] = blame.get(stage, 0.0) + seg
        executing[stage] = executing.get(stage, 0.0) + ex
        waiting[stage] = waiting.get(stage, 0.0) + (seg - ex)

    if execs:
        def preds(key):
            c, s = key
            out = []
            for ps in reversed(STAGE_ORDER[:_ORDER_IDX[s]]):
                if (c, ps) in execs:
                    out.append((c, ps))
                    break
            if (c - 1, s) in execs:
                out.append((c - 1, s))
            return out

        cur = max(execs, key=lambda k: execs[k][1])
        last_end = execs[cur][1]
        # trailing segment after the last pipeline event — the consumer
        # drained rows / assembled the tail of the report
        if t_end > last_end:
            charge('report', t_end - last_end, 0.0)
        t_hi = last_end
        # the walk strictly decreases (chunk + stage index); bound it
        for _ in range(len(execs) + len(STAGE_ORDER) + 2):
            if cur is None:
                break
            n0, n1, _busy = execs[cur]
            ps = preds(cur)
            gate = max(ps, key=lambda k: execs[k][1]) if ps else None
            lo = execs[gate][1] if gate is not None else t0
            lo = min(lo, t_hi)
            seg = t_hi - lo
            ex = max(0.0, min(t_hi, n1) - max(lo, n0))
            charge(cur[1], seg, min(ex, seg))
            t_hi = lo
            cur = gate
    else:
        charge('report', wall, 0.0)

    total = sum(blame.values())
    frac = {s: (v / total if total > 0 else 0.0)
            for s, v in blame.items()}
    bound_by = max(blame, key=lambda s: blame[s]) if blame else ''
    suggest, note = advise(bound_by, frac.get(bound_by, 0.0))
    blame_r = {s: round(v, 6) for s, v in blame.items()}
    executing_r = {s: round(v, 6) for s, v in executing.items()}
    # waiting derives from the rounded pair so the executing+waiting ==
    # blame partition survives rounding exactly
    waiting_r = {s: round(max(0.0, blame_r[s] - executing_r.get(s, 0.0)), 6)
                 for s in blame_r}
    return {
        'wall_s': round(wall, 6),
        'blame_s': blame_r,
        'blame_frac': {s: round(v, 4) for s, v in frac.items()},
        'executing_s': executing_r,
        'waiting_s': waiting_r,
        'bound_by': bound_by,
        'suggest': suggest,
        'note': note,
        'chunks': len({c for c, _s in execs}),
    }


def advise(bound_by: str, frac: float,
           detail: str = '') -> Tuple[Dict[str, str], str]:
    """Turn a blame verdict into concrete knob deltas.

    Returns ``(suggest, note)``: env-knob deltas worth trying plus a
    one-line rationale.  Deliberately coarse — the observatory names
    the wall to push on, the operator (or the bench sweep) confirms.
    ``detail`` carries verdict-specific context (the fleet skew
    analyzer passes the straggler's shard/device identity).
    """
    pct = f'{frac * 100:.0f}%'
    if bound_by == 'straggler':
        # fed by the fleet skew analyzer (observability/fleet.py):
        # one shard's device-eval wall dominates a sustained window of
        # mesh steps — no host-pipeline knob fixes a slow device
        who = detail or 'one shard'
        return ({},
                f'mesh straggler: {who} carries {pct} excess '
                f'device-eval wall over the skew window — rebalance '
                f'or drain that device/host; deepening the host '
                f'pipeline cannot help a slow shard')
    if bound_by == 'encode':
        return ({'KTPU_ENCODE_PROCS': '+2', 'KTPU_PIPELINE_DEPTH': '+1'},
                f'host encode holds {pct} of the critical path: add '
                f'forked encode workers and a pipeline slot so h2d '
                f'never starves')
    if bound_by in ('h2d', 'd2h'):
        return ({'KTPU_PIPELINE_DEPTH': '+1'},
                f'{bound_by} transfer holds {pct} of the critical '
                f'path: deepen the pipeline so transfers overlap more '
                f'compute')
    if bound_by in ('device_eval', 'compile', 'pack'):
        return ({},
                f'{bound_by} holds {pct} of the critical path: the '
                f'host pipeline keeps the device fed — speedups must '
                f'come from the kernel/compile side, not more overlap')
    if bound_by == 'report':
        return ({'KTPU_REPORT_FLUSH_ROWS': 'x2'},
                f'report assembly holds {pct} of the critical path: '
                f'widen the flush window or thin the per-row work')
    return ({}, '')


def format_summary(summary: Optional[Dict[str, Any]]) -> str:
    """Compact single-attr rendering for spans:
    ``bound_by=<s> <stage>=<frac> ...`` in descending blame order."""
    if not summary:
        return ''
    frac = summary.get('blame_frac') or {}
    parts = ['bound_by=%s' % summary.get('bound_by', '')]
    for s, f in sorted(frac.items(), key=lambda kv: -kv[1]):
        parts.append('%s=%.2f' % (s, f))
    return ' '.join(parts)


# -- recorder -----------------------------------------------------------------


class TimelineRecorder:
    """Process-wide home for finished scan timelines.

    Keeps the last ``max_scans`` timelines for trace export, cumulative
    per-stage blame totals for the metric/bench deltas, and the most
    recent summary for the debug endpoint.
    """

    def __init__(self, max_events: int, max_scans: int = 16):
        self.max_events = max_events
        self._seq = itertools.count(1)
        self._scans: "deque[ScanTimeline]" = deque(maxlen=max_scans)
        self._lock = threading.Lock()
        self._blame_totals: Dict[str, float] = {}
        self._wall_total = 0.0
        self.n_scans = 0
        self.last_summary: Optional[Dict[str, Any]] = None

    def begin(self) -> ScanTimeline:
        return ScanTimeline(next(self._seq), self.max_events)

    def finish(self, tl: ScanTimeline) -> Dict[str, Any]:
        summary = tl.finalize()
        with self._lock:
            for s, v in summary['blame_s'].items():
                self._blame_totals[s] = self._blame_totals.get(s, 0.0) + v
            self._wall_total += summary['wall_s']
            self.n_scans += 1
            self.last_summary = summary
            self._scans.append(tl)
        reg = devtel.registry()
        if reg is not None:
            for s, v in summary['blame_s'].items():
                if v > 0:
                    reg.inc(PIPELINE_BLAME, v, stage=s)
        cap = devtel.current_capture()
        if cap is not None:
            cap.critical_path = summary
        return summary

    def blame_totals(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._blame_totals)

    def wall_total(self) -> float:
        with self._lock:
            return self._wall_total

    def scans(self) -> List[ScanTimeline]:
        with self._lock:
            return list(self._scans)

    def chrome_trace(self) -> Dict[str, Any]:
        return chrome_trace(self.scans())


# -- module state -------------------------------------------------------------

_recorder: Optional[TimelineRecorder] = None
_tl_var: "contextvars.ContextVar[Optional[ScanTimeline]]" = \
    contextvars.ContextVar('ktpu_timeline', default=None)


def configure(max_events: Optional[int] = None,
              max_scans: int = 16) -> Optional[TimelineRecorder]:
    """Arm the recorder.  ``KTPU_TIMELINE=0`` wins: stays off, returns
    None, and every scan-path hook stays on its zero-cost branch."""
    global _recorder
    if os.environ.get('KTPU_TIMELINE', '1') == '0':
        _recorder = None
        return None
    if max_events is None:
        try:
            max_events = int(os.environ.get('KTPU_TIMELINE_N', '4096'))
        except ValueError:
            max_events = 4096
    _recorder = TimelineRecorder(max(max_events, 16), max_scans)
    return _recorder


def disable() -> None:
    global _recorder
    _recorder = None


def recorder() -> Optional[TimelineRecorder]:
    return _recorder


def enabled() -> bool:
    return _recorder is not None


def begin_scan() -> Optional[ScanTimeline]:
    rec = _recorder
    return rec.begin() if rec is not None else None


def finish_scan(tl: Optional[ScanTimeline]) -> Optional[Dict[str, Any]]:
    if tl is None:
        return None
    rec = _recorder
    if rec is None:
        return tl.finalize()
    return rec.finish(tl)


def blame_totals() -> Dict[str, float]:
    rec = _recorder
    return rec.blame_totals() if rec is not None else {}


def last_critical_path() -> Optional[Dict[str, Any]]:
    rec = _recorder
    return rec.last_summary if rec is not None else None


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SCOPE = _NoopScope()


class _ExecScope:
    __slots__ = ('_tl', '_chunk', '_stage')

    def __init__(self, tl, chunk, stage):
        self._tl = tl
        self._chunk = chunk
        self._stage = stage

    def __enter__(self):
        self._tl.start(self._chunk, self._stage)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tl.end(self._chunk, self._stage, ok=exc_type is None)
        return False


def exec_scope(tl: Optional[ScanTimeline], chunk: int, stage: str):
    """Context manager recording an exec interval; free no-op when the
    timeline is off (the inline single-chunk path wraps stages in it
    unconditionally)."""
    if tl is None:
        return _NOOP_SCOPE
    return _ExecScope(tl, chunk, stage)


# -- Chrome-trace / Perfetto export -------------------------------------------


def chrome_trace(timelines: List[ScanTimeline]) -> Dict[str, Any]:
    """Render timelines as Chrome trace-event JSON (Perfetto loads it
    directly): one pid per scan, one tid per worker thread, complete
    'X' events per interval plus 'M' name metadata."""
    out: List[Dict[str, Any]] = []
    if not timelines:
        return {'traceEvents': out, 'displayTimeUnit': 'ms'}
    base = min(tl.t0 for tl in timelines)
    for tl in timelines:
        pid = tl.scan_id
        tids: Dict[str, int] = {}
        out.append({'name': 'process_name', 'ph': 'M', 'pid': pid,
                    'tid': 0, 'args': {'name': 'scan-%d' % pid}})
        for ev in tl.events:
            tid = tids.get(ev.thread)
            if tid is None:
                tid = tids[ev.thread] = len(tids) + 1
                out.append({'name': 'thread_name', 'ph': 'M', 'pid': pid,
                            'tid': tid, 'args': {'name': ev.thread}})
            args: Dict[str, Any] = {'chunk': ev.chunk, 'kind': ev.kind}
            if ev.attempt:
                args['attempt'] = ev.attempt
            out.append({
                'name': ev.stage if ev.kind == 'exec'
                else '%s:%s' % (ev.stage, ev.kind),
                'cat': ev.kind,
                'ph': 'X',
                'ts': round((ev.t0 - base) * 1e6, 3),
                'dur': round(max(0.0, ev.t1 - ev.t0) * 1e6, 3),
                'pid': pid,
                'tid': tid,
                'args': args,
            })
    return {'traceEvents': out, 'displayTimeUnit': 'ms'}


def validate_chrome_trace(trace: Any) -> List[str]:
    """Check a Chrome-trace document against the trace-event schema
    subset we emit/accept: a traceEvents list whose entries are 'M'
    metadata, complete 'X' events (numeric ts ≥ 0 and dur ≥ 0), or
    matched 'B'/'E' pairs with per-(pid,tid) monotonic timestamps.
    Returns a list of human-readable violations (empty == valid)."""
    errors: List[str] = []
    events = trace.get('traceEvents') if isinstance(trace, dict) else trace
    if not isinstance(events, list):
        return ['traceEvents: missing or not a list']
    stacks: Dict[Tuple[Any, Any], List[str]] = {}
    last_ts: Dict[Tuple[Any, Any], float] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append('event %d: not an object' % i)
            continue
        ph = ev.get('ph')
        if ph == 'M':
            continue
        if ph not in ('X', 'B', 'E'):
            errors.append('event %d: unsupported ph=%r' % (i, ph))
            continue
        ts = ev.get('ts')
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append('event %d: bad ts=%r' % (i, ts))
            continue
        key = (ev.get('pid'), ev.get('tid'))
        if ph == 'X':
            dur = ev.get('dur')
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append('event %d: X event bad dur=%r' % (i, dur))
        else:
            if ts < last_ts.get(key, float('-inf')):
                errors.append(
                    'event %d: ts %r not monotonic on pid/tid %r'
                    % (i, ts, key))
            last_ts[key] = ts
            stack = stacks.setdefault(key, [])
            if ph == 'B':
                stack.append(ev.get('name', ''))
            else:
                if not stack:
                    errors.append(
                        'event %d: E without matching B on pid/tid %r'
                        % (i, key))
                else:
                    stack.pop()
    for key, stack in stacks.items():
        for name in stack:
            errors.append('unclosed B event %r on pid/tid %r'
                          % (name, key))
    return errors


def blame_from_chrome(trace: Any) -> Dict[str, Any]:
    """Reconstruct per-scan blame from an exported trace file (the
    offline path for ``scripts/timeline_report.py``): groups exec 'X'
    events by pid, reruns the analyzer per scan, and sums."""
    events = trace.get('traceEvents') if isinstance(trace, dict) else trace
    per_pid: Dict[Any, List[StageEvent]] = {}
    for ev in events or []:
        if not isinstance(ev, dict) or ev.get('ph') != 'X':
            continue
        args = ev.get('args') or {}
        kind = args.get('kind', ev.get('cat', 'exec'))
        t0 = float(ev.get('ts', 0)) / 1e6
        t1 = t0 + float(ev.get('dur', 0)) / 1e6
        name = ev.get('name', '')
        stage = name.split(':', 1)[0]
        per_pid.setdefault(ev.get('pid'), []).append(StageEvent(
            args.get('chunk', -1), stage, kind, t0, t1,
            str(ev.get('tid', '')), args.get('attempt', 0)))
    scans = []
    totals: Dict[str, float] = {}
    wall = 0.0
    for pid in sorted(per_pid, key=lambda p: (str(type(p)), str(p))):
        evs = per_pid[pid]
        lo = min(e.t0 for e in evs)
        hi = max(e.t1 for e in evs)
        summary = analyze(evs, lo, hi)
        summary['scan_id'] = pid
        scans.append(summary)
        wall += summary['wall_s']
        for s, v in summary['blame_s'].items():
            totals[s] = totals.get(s, 0.0) + v
    total = sum(totals.values())
    frac = {s: (v / total if total > 0 else 0.0) for s, v in totals.items()}
    bound_by = max(totals, key=lambda s: totals[s]) if totals else ''
    suggest, note = advise(bound_by, frac.get(bound_by, 0.0))
    return {
        'scans': scans,
        'blame_s': {s: round(v, 6) for s, v in totals.items()},
        'blame_frac': {s: round(v, 4) for s, v in frac.items()},
        'wall_s': round(wall, 6),
        'bound_by': bound_by,
        'suggest': suggest,
        'note': note,
    }


def dump_chrome_trace(path: str) -> Optional[str]:
    """Write the recorder's current trace to ``path`` (creating parent
    dirs); returns the path, or None when the recorder is off."""
    rec = _recorder
    if rec is None:
        return None
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, 'w') as fh:
        json.dump(rec.chrome_trace(), fh)
    return path
