"""PolicyReport pipeline (reference: api/policyreport/v1alpha2,
pkg/utils/report, pkg/controllers/report)."""

from .aggregate import AggregateController  # noqa: F401
from .results import (  # noqa: F401
    calculate_summary, engine_response_to_report_results,
    sort_report_results, split_results_by_policy,
)
from .types import (  # noqa: F401
    build_admission_report, calculate_resource_hash,
    new_background_scan_report, new_policy_report, policy_label,
)