"""Report aggregation (reference:
pkg/controllers/report/aggregate/controller.go).

Merges per-resource AdmissionReports and BackgroundScanReports into
namespaced PolicyReports / cluster-scoped ClusterPolicyReports, one per
policy (``cpol-<name>`` / ``pol-<name>``), keeping only results for
policies and rules that still exist and preferring the newest result per
(policy, rule, resource-uid).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..api.policy import Policy, Rule
from ..autogen.autogen import compute_rules
from ..dclient.client import NotFoundError
from .results import set_results
from .types import (
    LABEL_APP_MANAGED_BY, VALUE_KYVERNO_APP, new_policy_report,
    set_managed_by_kyverno_label, set_policy_label,
)

_SOURCE_KINDS = (
    ('kyverno.io/v1alpha2', 'AdmissionReport'),
    ('kyverno.io/v1alpha2', 'ClusterAdmissionReport'),
    ('kyverno.io/v1alpha2', 'BackgroundScanReport'),
    ('kyverno.io/v1alpha2', 'ClusterBackgroundScanReport'),
)


class AggregateController:
    """reference: aggregate/controller.go:46"""

    def __init__(self, client, policy_lister=None):
        self.client = client
        # policy_lister() -> List[Policy]; defaults to the client store
        self.policy_lister = policy_lister or self._list_policies

    def _list_policies(self) -> List[Policy]:
        out = []
        for api_version in ('kyverno.io/v1', 'kyverno.io/v2beta1'):
            for kind in ('ClusterPolicy', 'Policy'):
                out += [Policy(p) for p in self.client.list_resource(
                    api_version, kind)]
        return out

    def _create_policy_map(self) -> Dict[str, Tuple[Policy, Set[str]]]:
        """reference: aggregate/controller.go:283 createPolicyMap"""
        out: Dict[str, Tuple[Policy, Set[str]]] = {}
        for policy in self.policy_lister():
            rules = {Rule(r).name for r in compute_rules(policy)}
            out[policy.get_kind_and_name()] = (policy, rules)
        return out

    def reconcile(self) -> List[dict]:
        """One full aggregation pass over every namespace (plus cluster
        scope). Returns the reconciled PolicyReport/ClusterPolicyReport
        objects (reference: reconcile + buildReportsResults)."""
        policy_map = self._create_policy_map()
        accumulator: Dict[str, dict] = {}
        for api_version, kind in _SOURCE_KINDS:
            for report in self.client.list_resource(api_version, kind):
                self._merge_report(policy_map, accumulator, report)
        # bucket merged results by namespace, then by per-policy report
        # name via the shared naming helper
        from .results import split_results_by_policy
        by_ns: Dict[str, List[dict]] = {}
        for result in accumulator.values():
            by_ns.setdefault(result.pop('_namespace', ''), []).append(result)
        buckets: Dict[Tuple[str, str], List[dict]] = {}
        for ns, ns_results in by_ns.items():
            for name, results in split_results_by_policy(ns_results).items():
                buckets[(ns, name)] = results
        reconciled = []
        for (ns, name), results in sorted(buckets.items()):
            reconciled.append(
                self._reconcile_report(policy_map, ns, name, results))
        self._clean_reports({(
            (r.get('metadata') or {}).get('namespace', ''),
            (r.get('metadata') or {}).get('name', ''))
            for r in reconciled})
        return reconciled

    def _merge_report(self, policy_map, accumulator: Dict[str, dict],
                      report: dict) -> None:
        """reference: aggregate/controller.go:254 mergeReports"""
        owner_refs = (report.get('metadata') or {}).get('ownerReferences') or []
        if len(owner_refs) != 1:
            return
        owner = owner_refs[0]
        ns = (report.get('metadata') or {}).get('namespace', '')
        object_ref = {
            'apiVersion': owner.get('apiVersion', ''),
            'kind': owner.get('kind', ''),
            'namespace': ns,
            'name': owner.get('name', ''),
            'uid': owner.get('uid', ''),
        }
        from .results import get_results
        for result in get_results(report):
            entry = policy_map.get(result.get('policy', ''))
            if entry is None or result.get('rule', '') not in entry[1]:
                continue
            key = (f"{result.get('policy', '')}/{result.get('rule', '')}/"
                   f"{owner.get('uid', '')}")
            merged = dict(result)
            merged['resources'] = [object_ref]
            merged['_namespace'] = ns
            current = accumulator.get(key)
            if current is None or \
                    (current.get('timestamp', {}).get('seconds', 0) <
                     merged.get('timestamp', {}).get('seconds', 0)):
                accumulator[key] = merged

    def _reconcile_report(self, policy_map, namespace: str, name: str,
                          results: List[dict]) -> dict:
        """reference: aggregate/controller.go:211 reconcileReport"""
        kind = 'PolicyReport' if namespace else 'ClusterPolicyReport'
        try:
            existing = self.client.get_resource(
                'wgpolicyk8s.io/v1alpha2', kind, namespace, name)
        except NotFoundError:
            existing = None
        if existing is None:
            report = new_policy_report(namespace, name, results)
            self._label_policies(report, policy_map, results)
            return self.client.create_resource(
                'wgpolicyk8s.io/v1alpha2', kind, namespace, report)
        import copy as _copy
        after = _copy.deepcopy(existing)
        after.setdefault('metadata', {})['labels'] = {}
        set_managed_by_kyverno_label(after)
        self._label_policies(after, policy_map, results)
        set_results(after, results)
        if after == existing:
            return after
        return self.client.update_resource(
            'wgpolicyk8s.io/v1alpha2', kind, namespace, after)

    @staticmethod
    def _label_policies(report: dict, policy_map, results: List[dict]) -> None:
        for result in results:
            entry = policy_map.get(result.get('policy', ''))
            if entry is not None:
                set_policy_label(report, entry[0])

    def _clean_reports(self, keep: Set[Tuple[str, str]]) -> None:
        """reference: aggregate/controller.go:238 cleanReports"""
        for kind in ('PolicyReport', 'ClusterPolicyReport'):
            for report in self.client.list_resource(
                    'wgpolicyk8s.io/v1alpha2', kind):
                meta = report.get('metadata') or {}
                labels = meta.get('labels') or {}
                # only reap kyverno-managed reports — third-party
                # PolicyReports (e.g. trivy-operator) are not ours
                # (reference: aggregate/controller.go report selector)
                if labels.get(LABEL_APP_MANAGED_BY) != VALUE_KYVERNO_APP:
                    continue
                key = (meta.get('namespace', ''), meta.get('name', ''))
                if key not in keep:
                    try:
                        self.client.delete_resource(
                            'wgpolicyk8s.io/v1alpha2', kind,
                            key[0], key[1])
                    except NotFoundError:
                        pass
