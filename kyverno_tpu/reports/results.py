"""EngineResponse → PolicyReport result mapping (reference:
pkg/utils/report/results.go). The judge-facing invariant: this mapping is
bit-identical to the reference (field names, result strings, warn
rewrite for unscored policies, sorted results, summary counts).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..engine.api import EngineResponse, RuleStatus

# reference: api/policyreport/v1alpha2/policyreport_types.go
STATUS_PASS = 'pass'
STATUS_FAIL = 'fail'
STATUS_WARN = 'warn'
STATUS_ERROR = 'error'
STATUS_SKIP = 'skip'

SEVERITIES = ('critical', 'high', 'medium', 'low', 'info')

ANNOTATION_POLICY_SCORED = 'policies.kyverno.io/scored'
ANNOTATION_POLICY_CATEGORY = 'policies.kyverno.io/category'
ANNOTATION_POLICY_SEVERITY = 'policies.kyverno.io/severity'

_STATUS_MAP = {
    RuleStatus.PASS: STATUS_PASS,
    RuleStatus.FAIL: STATUS_FAIL,
    RuleStatus.ERROR: STATUS_ERROR,
    RuleStatus.WARN: STATUS_WARN,
    RuleStatus.SKIP: STATUS_SKIP,
}


def to_policy_result(status: str) -> str:
    """reference: results.go:56 toPolicyResult"""
    return _STATUS_MAP.get(status, '')


def severity_from_string(severity: str) -> str:
    """reference: results.go:72 severityFromString (high/medium/low)"""
    if severity in ('high', 'medium', 'low'):
        return severity
    return ''


# per-policy static template cache: the policy key / scored flag /
# category / severity never vary between rules of one policy, and batch
# scans map millions of rules — re-deriving them per rule dominates
# report construction (keyed by id(); the tiny bound makes stale-id
# reuse harmless since entries also store the policy for identity check)
_POLICY_STATIC_CACHE: Dict[int, tuple] = {}


def _policy_static(policy) -> dict:
    pid = id(policy)
    hit = _POLICY_STATIC_CACHE.get(pid)
    if hit is not None and hit[0] is policy:
        return hit[1]
    annotations = policy.annotations if policy else {}
    template = (
        policy.get_kind_and_name() if policy else '',
        annotations.get(ANNOTATION_POLICY_SCORED) != 'false',
        annotations.get(ANNOTATION_POLICY_CATEGORY),
        severity_from_string(
            annotations.get(ANNOTATION_POLICY_SEVERITY, '')),
    )
    if len(_POLICY_STATIC_CACHE) > 4096:
        _POLICY_STATIC_CACHE.clear()
    _POLICY_STATIC_CACHE[pid] = (policy, template)
    return template


# flyweight report-result cache: batch scans share RuleResponse objects
# across resources (scan.py flyweights), so the result dict for one
# (rule response, policy, second) triple is identical for every resource
# — reuse it instead of rebuilding.  Consumers treat report results as
# immutable (they are serialized into CRs, never mutated in place).
# Keyed by id() with the rule response pinned in the value for identity
# verification, like _POLICY_STATIC_CACHE.
_RESULT_CACHE: Dict[int, tuple] = {}


def _rule_result(rule, key: str, scored: bool, category, severity,
                 ts: dict, now: int) -> dict:
    rid = id(rule)
    hit = _RESULT_CACHE.get(rid)
    if hit is not None and hit[0] is rule and hit[1] == now \
            and hit[2] == key:
        return hit[3]
    r = to_policy_result(rule.status)
    if r == STATUS_FAIL and not scored:
        r = STATUS_WARN
    result = {
        'source': 'kyverno',
        'policy': key,
        'rule': rule.name,
        'message': rule.message,
        'result': r,
        'scored': scored,
        'timestamp': ts,
    }
    if category:
        result['category'] = category
    if severity:
        result['severity'] = severity
    checks = rule.pod_security_checks
    if checks:
        controls = sorted(c['id'] for c in checks.get('checks', [])
                          if not c.get('allowed', True))
        if controls:
            result['properties'] = {
                'standard': checks.get('level', ''),
                'version': checks.get('version', ''),
                'controls': ','.join(controls),
            }
    if len(_RESULT_CACHE) > 16384:
        _RESULT_CACHE.clear()
    _RESULT_CACHE[rid] = (rule, now, key, result)
    return result


def engine_response_to_report_results(response: EngineResponse,
                                      now: Optional[int] = None
                                      ) -> List[dict]:
    """reference: results.go:84 EngineResponseToReportResults"""
    policy = response.policy
    key, scored, category, severity = _policy_static(policy)
    if now is None:
        now = int(time.time())
    ts = {'seconds': now}
    return [_rule_result(rule, key, scored, category, severity, ts, now)
            for rule in response.policy_response.rules]


def sort_report_results(results: List[dict]) -> None:
    """reference: results.go:18 SortReportResults"""
    def key(r: dict):
        resources = r.get('resources') or []
        # timestamps compare as strings on purpose: the reference sorts on
        # metav1.Timestamp.String() (results.go:33), which is lexicographic
        return (r.get('policy', ''), r.get('rule', ''), len(resources),
                tuple(res.get('uid', '') for res in resources),
                str(r.get('timestamp', {}).get('seconds', 0)))
    results.sort(key=key)


def calculate_summary(results: List[dict]) -> Dict[str, int]:
    """reference: results.go:38 CalculateSummary"""
    summary = {'pass': 0, 'fail': 0, 'warn': 0, 'error': 0, 'skip': 0}
    for r in results:
        status = r.get('result', '')
        if status in summary:
            summary[status] += 1
    return summary


def split_results_by_policy(results: List[dict]) -> Dict[str, List[dict]]:
    """reference: results.go:124 SplitResultsByPolicy — group results per
    policy under 'cpol-<name>' / 'pol-<name>' report names."""
    out: Dict[str, List[dict]] = {}
    for result in results:
        policy_key = result.get('policy', '')
        if '/' in policy_key:
            key = 'pol-' + policy_key.split('/', 1)[1]
        else:
            key = 'cpol-' + policy_key
        out.setdefault(key, []).append(result)
    return out


def _results_in_spec(report: dict) -> bool:
    """Intermediate kyverno.io report CRs ({Cluster,}AdmissionReport,
    {Cluster,}BackgroundScanReport) carry results/summary under .spec
    (reference: api/kyverno/v1alpha2/background_scan_report_types.go:62
    SetResults → r.Spec.Results); the final wgpolicyk8s.io PolicyReports
    keep them at top level."""
    return str(report.get('apiVersion', '')).startswith('kyverno.io/')


def get_results(report: dict) -> List[dict]:
    if _results_in_spec(report):
        return (report.get('spec') or {}).get('results') or []
    return report.get('results') or []


def set_results(report: dict, results: List[dict]) -> None:
    """reference: results.go:153 SetResults — sort + summary."""
    results = list(results)
    sort_report_results(results)
    target = report.setdefault('spec', {}) if _results_in_spec(report) \
        else report
    target['results'] = results
    target['summary'] = calculate_summary(results)


def set_fused_results(report: dict, results: List[dict], summary: dict,
                      policies) -> None:
    """Attach pre-built (already sorted) scan results to a report — the
    fused-path sibling of ``set_responses`` fed by
    BatchScanner.scan_report_results."""
    from .types import set_policy_label
    for policy in policies:
        set_policy_label(report, policy)
    target = report.setdefault('spec', {}) if _results_in_spec(report) \
        else report
    target['results'] = list(results)
    target['summary'] = dict(summary)


def set_responses(report: dict, *responses: EngineResponse,
                  now: Optional[int] = None) -> None:
    """reference: results.go:159 SetResponses"""
    from .types import set_policy_label
    results: List[dict] = []
    for resp in responses:
        if resp.policy is not None:
            set_policy_label(report, resp.policy)
        results.extend(engine_response_to_report_results(resp, now))
    set_results(report, results)
