"""Report CR builders and labels (reference: pkg/utils/report/{new,labels}.go,
api/kyverno/v1alpha2, api/policyreport/v1alpha2).
"""

from __future__ import annotations

import hashlib
import json
from typing import List, Optional

from ..api.policy import Policy

LABEL_RESOURCE_HASH = 'audit.kyverno.io/resource.hash'
LABEL_RESOURCE_UID = 'audit.kyverno.io/resource.uid'
LABEL_DOMAIN_CLUSTER_POLICY = 'cpol.kyverno.io'
LABEL_DOMAIN_POLICY = 'pol.kyverno.io'
LABEL_AGGREGATED_REPORT = 'audit.kyverno.io/report.aggregate'
LABEL_APP_MANAGED_BY = 'app.kubernetes.io/managed-by'
VALUE_KYVERNO_APP = 'kyverno'


# (policy id) → (policy, label, resourceVersion): label derivation runs
# per (report, policy) pair during batch scans — millions of calls for
# a value that is constant per policy object
_POLICY_LABEL_CACHE: dict = {}


def policy_label(policy: Policy) -> str:
    """reference: labels.go:61 PolicyLabel"""
    return _policy_label_rv(policy)[0]


def _policy_label_rv(policy: Policy):
    pid = id(policy)
    hit = _POLICY_LABEL_CACHE.get(pid)
    if hit is not None and hit[0] is policy:
        return hit[1], hit[2]
    domain = LABEL_DOMAIN_POLICY if policy.is_namespaced \
        else LABEL_DOMAIN_CLUSTER_POLICY
    label = f'{domain}/{policy.name}'
    rv = policy.metadata.get('resourceVersion', '') or ''
    if len(_POLICY_LABEL_CACHE) > 4096:
        _POLICY_LABEL_CACHE.clear()
    _POLICY_LABEL_CACHE[pid] = (policy, label, rv)
    return label, rv


def is_policy_label(label: str) -> bool:
    """reference: labels.go:31 IsPolicyLabel"""
    return label.startswith(f'{LABEL_DOMAIN_POLICY}/') or \
        label.startswith(f'{LABEL_DOMAIN_CLUSTER_POLICY}/')


def policy_name_from_label(namespace: str, label: str) -> str:
    """reference: labels.go:35 PolicyNameFromLabel"""
    parts = label.split('/')
    if len(parts) == 2:
        if parts[0] == LABEL_DOMAIN_CLUSTER_POLICY:
            return parts[1]
        if parts[0] == LABEL_DOMAIN_POLICY:
            return f'{namespace}/{parts[1]}'
    raise ValueError(
        f'cannot get policy name from label, incorrect format: {label}')


def _set_label(obj: dict, key: str, value: str) -> None:
    obj.setdefault('metadata', {}).setdefault('labels', {})[key] = value


def set_managed_by_kyverno_label(obj: dict) -> None:
    _set_label(obj, LABEL_APP_MANAGED_BY, VALUE_KYVERNO_APP)


def set_policy_label(report: dict, policy: Policy) -> None:
    """reference: labels.go:100 SetPolicyLabel — value is the policy's
    resourceVersion so report controllers detect stale results."""
    label, rv = _policy_label_rv(policy)
    _set_label(report, label, rv)


def set_resource_labels(report: dict, uid: str) -> None:
    _set_label(report, LABEL_RESOURCE_UID, uid)


def calculate_resource_hash(resource: dict) -> str:
    """reference: labels.go:73 CalculateResourceHash — md5 over
    [labels, annotations, object minus metadata/status/scale/nodeName].
    Shallow-copies only the containers it prunes (json.dumps never
    mutates): the old deepcopy dominated background-reconcile ticks at
    two calls per row."""
    meta = resource.get('metadata') or {}
    obj = {k: v for k, v in resource.items()
           if k not in ('metadata', 'status', 'scale')}
    spec = obj.get('spec')
    if isinstance(spec, dict) and 'nodeName' in spec:
        obj['spec'] = {k: v for k, v in spec.items() if k != 'nodeName'}
    data = json.dumps([meta.get('labels'), meta.get('annotations'), obj],
                      separators=(',', ':'), sort_keys=True)
    return hashlib.md5(data.encode()).hexdigest()  # noqa: S324 — parity


def set_resource_version_labels(report: dict, resource: Optional[dict],
                                resource_hash: Optional[str] = None
                                ) -> None:
    """``resource_hash`` short-circuits the hash when the caller already
    holds it (the metadata cache computes it on every update)."""
    if resource_hash is None:
        resource_hash = calculate_resource_hash(resource) if resource \
            else ''
    _set_label(report, LABEL_RESOURCE_HASH, resource_hash)


def _owner_reference(resource: dict) -> dict:
    meta = resource.get('metadata') or {}
    return {
        'apiVersion': resource.get('apiVersion', ''),
        'kind': resource.get('kind', ''),
        'name': meta.get('name', ''),
        'uid': meta.get('uid', ''),
    }


def new_admission_report(namespace: str, name: str, owner_resource: dict
                         ) -> dict:
    """reference: new.go:15 NewAdmissionReport"""
    kind = 'AdmissionReport' if namespace else 'ClusterAdmissionReport'
    report = {
        'apiVersion': 'kyverno.io/v1alpha2',
        'kind': kind,
        'metadata': {
            'name': name,
            'ownerReferences': [_owner_reference(owner_resource)],
        },
        'spec': {'owner': _owner_reference(owner_resource)},
    }
    if namespace:
        report['metadata']['namespace'] = namespace
    uid = (owner_resource.get('metadata') or {}).get('uid', '')
    set_resource_labels(report, uid)
    set_managed_by_kyverno_label(report)
    return report


def build_admission_report(resource: dict, request: dict,
                           *responses, now: Optional[int] = None) -> dict:
    """reference: new.go:35 BuildAdmissionReport"""
    from .results import set_responses
    meta = resource.get('metadata') or {}
    report = new_admission_report(meta.get('namespace', ''),
                                  str(request.get('uid', '')), resource)
    set_responses(report, *responses, now=now)
    return report


def new_background_scan_report(resource: dict) -> dict:
    """reference: new.go:42 NewBackgroundScanReport"""
    meta = resource.get('metadata') or {}
    namespace = meta.get('namespace', '')
    kind = 'BackgroundScanReport' if namespace else \
        'ClusterBackgroundScanReport'
    report = {
        'apiVersion': 'kyverno.io/v1alpha2',
        'kind': kind,
        'metadata': {
            'name': meta.get('uid', '') or meta.get('name', ''),
            'ownerReferences': [_owner_reference(resource)],
        },
    }
    if namespace:
        report['metadata']['namespace'] = namespace
    set_managed_by_kyverno_label(report)
    return report


# label-dict template per distinct policy tuple: the streaming report
# path stamps the same policy set onto every row's report, so the
# managed-by + per-policy labels prebuild once and each report pays one
# C-level dict copy (id-keyed with identity re-verification, like
# _POLICY_LABEL_CACHE)
_FUSED_LABEL_CACHE: dict = {}


def _fused_labels(policies) -> dict:
    lkey = tuple(id(p) for p in policies)
    hit = _FUSED_LABEL_CACHE.get(lkey)
    if hit is not None and len(hit[0]) == len(policies) and \
            all(a is b for a, b in zip(hit[0], policies)):
        return hit[1]
    labels = {LABEL_APP_MANAGED_BY: VALUE_KYVERNO_APP}
    for policy in policies:
        label, rv = _policy_label_rv(policy)
        labels[label] = rv
    if len(_FUSED_LABEL_CACHE) > 4096:
        _FUSED_LABEL_CACHE.clear()
    _FUSED_LABEL_CACHE[lkey] = (tuple(policies), labels)
    return labels


def build_fused_report(resource: dict, results: List[dict], summary: dict,
                       policies) -> dict:
    """One-shot BackgroundScanReport for the streaming scan path:
    equivalent to ``new_background_scan_report`` + ``set_policy_label``
    per policy + ``set_fused_results``, built as a single literal with
    the label dict copied from a per-policy-set template — the report
    materialization leg of the 1M-row stream runs ~3x fewer dict
    operations per row."""
    meta = resource.get('metadata') or {}
    namespace = meta.get('namespace', '')
    report_meta = {
        'name': meta.get('uid', '') or meta.get('name', ''),
        'ownerReferences': [_owner_reference(resource)],
    }
    if namespace:
        report_meta['namespace'] = namespace
    report_meta['labels'] = dict(_fused_labels(policies))
    return {
        'apiVersion': 'kyverno.io/v1alpha2',
        'kind': 'BackgroundScanReport' if namespace
                else 'ClusterBackgroundScanReport',
        'metadata': report_meta,
        'spec': {'results': list(results), 'summary': dict(summary)},
    }


def new_policy_report(namespace: str, name: str,
                      results: Optional[List[dict]] = None) -> dict:
    """reference: new.go:57 NewPolicyReport"""
    from .results import set_results
    kind = 'PolicyReport' if namespace else 'ClusterPolicyReport'
    report = {
        'apiVersion': 'wgpolicyk8s.io/v1alpha2',
        'kind': kind,
        'metadata': {'name': name},
    }
    if namespace:
        report['metadata']['namespace'] = namespace
    set_managed_by_kyverno_label(report)
    set_results(report, results or [])
    return report
