"""Report-side controllers: resource metadata cache, background scanner,
admission-report dedup (reference: pkg/controllers/report/{resource,
background,admission}/controller.go).

The background scan is where the TPU path plugs into the control plane:
instead of the reference's per-resource workqueue loop calling the
engine once per (resource, policy), pending resources drain in batches
through ``BatchScanner`` — the device evaluates the whole
[resources × rules] verdict matrix in one shot and only non-pass
entries touch the host engine.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..api.policy import Policy
from ..api.unstructured import Resource
from ..compiler.scan import BatchScanner
from ..engine.engine import Engine
from ..verdictcache.keys import spec_digest
from .results import set_responses
from .types import (calculate_resource_hash, new_background_scan_report,
                    set_managed_by_kyverno_label,
                    set_resource_version_labels)

ANNOTATION_LAST_SCAN_TIME = 'audit.kyverno.io/last-scan-time'


class MetadataCache:
    """Resource-metadata cache keyed by uid
    (reference: pkg/controllers/report/resource/controller.go
    MetadataCache): tracks the resource versions/hashes the scanner uses
    for invalidation.  ``add_invalidator`` registers uid-keyed hooks the
    cache calls on every content change or delete — the watch/
    resourceVersion delta the verdict cache rides for free."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self._invalidators: List[Callable[[str], Any]] = []

    def add_invalidator(self, fn: Callable[[str], Any]) -> None:
        """``fn(uid)`` runs (outside the cache lock) whenever a
        resource's hash changes or the resource is removed."""
        self._invalidators.append(fn)

    def _invalidate(self, uid: str) -> None:
        for fn in self._invalidators:
            try:
                fn(uid)
            except Exception:  # noqa: BLE001 - hooks must not break sync
                pass

    def update(self, resource: dict) -> bool:
        """Record a resource; returns True when its hash changed."""
        meta = resource.get('metadata') or {}
        uid = meta.get('uid') or f"{resource.get('kind')}/" \
            f"{meta.get('namespace', '')}/{meta.get('name', '')}"
        h = calculate_resource_hash(resource)
        with self._lock:
            old = self._entries.get(uid)
            self._entries[uid] = {
                'uid': uid,
                'kind': resource.get('kind', ''),
                'apiVersion': resource.get('apiVersion', ''),
                'namespace': meta.get('namespace', ''),
                'name': meta.get('name', ''),
                'hash': h,
                # verdict-cache key, computed once per content change
                # instead of once per reconcile tick over every row
                'digest': spec_digest(resource),
                'resource': resource,
            }
        changed = old is None or old['hash'] != h
        if changed and old is not None:
            self._invalidate(uid)
        return changed

    def remove(self, resource: dict) -> None:
        """Forget a deleted resource — and drop its verdict-cache rows
        via the invalidators, so a recreated resource with a stale uid
        can never replay old verdicts."""
        meta = resource.get('metadata') or {}
        uid = meta.get('uid') or f"{resource.get('kind')}/" \
            f"{meta.get('namespace', '')}/{meta.get('name', '')}"
        with self._lock:
            self._entries.pop(uid, None)
        self._invalidate(uid)

    def entries(self) -> List[dict]:
        with self._lock:
            return list(self._entries.values())

    def get(self, uid: str) -> Optional[dict]:
        with self._lock:
            return self._entries.get(uid)


class ResourceController:
    """Watches the resource kinds matched by the live policy set and
    keeps the MetadataCache in sync (reference:
    report/resource/controller.go:342)."""

    def __init__(self, client, cache: Optional[MetadataCache] = None,
                 on_change: Optional[Callable[[dict], None]] = None):
        self.client = client
        self.cache = cache or MetadataCache()
        self.on_change = on_change
        self._kinds: Set[str] = set()

    def update_policies(self, policies: List[Policy]) -> None:
        kinds: Set[str] = set()
        for policy in policies:
            for rule in policy.rules:
                match = rule.raw.get('match') or {}
                for f in [match] + (match.get('any') or []) + \
                        (match.get('all') or []):
                    for k in (f.get('resources') or {}).get('kinds') or []:
                        kinds.add(str(k).split('/')[-1])
        self._kinds = kinds

    def sync(self) -> List[dict]:
        """Poll-list the watched kinds; returns changed resources
        (informer events in the reference)."""
        changed = []
        for kind in sorted(self._kinds):
            try:
                items = self.client.list_resource('', kind, '', None)
            except Exception:  # noqa: BLE001
                continue
            for item in items:
                if self.cache.update(item):
                    changed.append(item)
                    if self.on_change is not None:
                        self.on_change(item)
        return changed


class BackgroundScanController:
    """Background-scan loop with last-scan-time resumability
    (reference: pkg/controllers/report/background/controller.go:40-46:
    2 workers / 30s enqueue delay; the batch path replaces the
    per-resource queue with device-evaluated chunks)."""

    def __init__(self, client, policies: List[Policy],
                 cache: Optional[MetadataCache] = None,
                 engine: Optional[Engine] = None):
        self.client = client
        self.cache = cache or MetadataCache()
        if engine is None and client is not None:
            from ..engine.apicall import make_context_loader
            engine = Engine(context_loader=make_context_loader(
                dclient=client))
        self.engine = engine or Engine()
        self._lock = threading.Lock()
        self._pending: Set[str] = set()
        self._scanned: Dict[str, Tuple[str, float]] = {}  # uid → (hash, ts)
        self._policy_epoch = 0.0
        self.verdict_cache = None
        #: per-reconcile rescan accounting (mirrors the
        #: kyverno_tpu_rescan_rows_* gauges for in-process readers)
        self.rescan_stats: Dict[str, int] = {
            'rows_pending': 0, 'rows_scanned': 0, 'rows_replayed': 0}
        self.set_policies(policies)
        # verdict-cache invalidation rides the metadata cache's
        # resourceVersion/delete deltas for free
        self.cache.add_invalidator(self._drop_verdicts)

    def set_policies(self, policies: List[Policy]) -> None:
        """Policy change invalidates every prior scan
        (reference: controller.go re-enqueues on policy events).  The
        verdict cache flushes by fingerprint: a changed policy set opens
        a new cache generation, so stale rows can never replay."""
        from ..aotcache import policy_set_fingerprint
        from ..verdictcache import VerdictCache
        self.policies = policies
        self.scanner = BatchScanner(policies, engine=self.engine)
        self._policy_index = {id(p): i for i, p in enumerate(policies)}
        # rows are only cacheable when every contributing result is a
        # pure function of (resource, policy set): host-riding policies
        # and context-loading rules consult external state per tick, so
        # their rows must re-evaluate on the dense path every time
        self._verdicts_cacheable = (
            not self.scanner._host_policy_idx and
            all(p.context_spec is None for p in self.scanner.cps.programs))
        old_cache = self.verdict_cache
        if old_cache is not None:
            old_cache.flush()
        self._policy_fingerprint = policy_set_fingerprint(policies)
        self.verdict_cache = None
        # partitioned generations (KTPU_PARTITIONS>0): verdict rows key
        # by partition fingerprint instead of the whole-set fingerprint,
        # so a policy edit only rolls the touched partitions' rows — and
        # the diff against the previous plan scopes the next reconcile's
        # rescan to the touched partitions' member policies
        old_plan = getattr(self, '_partition_plan', None)
        self._partition_plan = None
        self._scoped_pids: frozenset = frozenset()
        self._scoped_scanner = None
        self._scoped_globals: Dict[int, int] = {}
        from ..partition.plan import env_partitions
        if env_partitions() > 0:
            from ..partition.plan import (PartitionError, build_plan,
                                          diff_plans)
            from ..verdictcache import PartitionedVerdictCache
            try:
                plan = build_plan(policies, env_partitions())
            except PartitionError:
                plan = None
            if plan is not None:
                self._partition_plan = plan
                self.verdict_cache = PartitionedVerdictCache.from_env(
                    plan, policies,
                    prev=old_cache if isinstance(
                        old_cache, PartitionedVerdictCache) else None)
                if old_plan is not None:
                    diff = diff_plans(old_plan, plan)
                    if diff.touched and diff.unchanged:
                        self._scoped_pids = frozenset(diff.touched)
        if self.verdict_cache is None and self._partition_plan is None:
            self.verdict_cache = VerdictCache.from_env(
                self._policy_fingerprint)
        with self._lock:
            self._policy_epoch = time.time()

    def _get_scoped_scanner(self) -> Optional[BatchScanner]:
        """Lazily build the scanner scoped to the touched partitions'
        member policies (the partition evaluator cache makes this
        near-free: the touched partitions were just compiled for the
        full scanner, and the scoped sub-set re-derives the same
        partition fingerprints)."""
        if not self._scoped_pids or self._partition_plan is None:
            return None
        if self._scoped_scanner is None:
            plan = self._partition_plan
            idx = [i for i in range(len(self.policies))
                   if plan.assignment[i] in self._scoped_pids]
            members = [self.policies[i] for i in idx]
            self._scoped_scanner = BatchScanner(members, engine=self.engine)
            self._scoped_globals = {id(p): g
                                    for p, g in zip(members, idx)}
        return self._scoped_scanner

    def _drop_verdicts(self, uid: str) -> None:
        vc = self.verdict_cache
        if vc is not None:
            vc.invalidate_uid(uid)

    def reset_scan_state(self) -> None:
        """Forget per-process resumability: the next reconcile rebuilds
        every enqueued resource's report (what a process restart or a
        report-repair pass demands).  With a warm verdict cache that
        full demand stays O(churn) — unchanged rows replay."""
        self._scanned.clear()

    def close(self) -> None:
        """Persist the verdict cache (daemon shutdown hook)."""
        vc = self.verdict_cache
        if vc is not None:
            vc.flush()

    def enqueue(self, resource: dict) -> None:
        self.cache.update(resource)
        meta = resource.get('metadata') or {}
        uid = meta.get('uid') or f"{resource.get('kind')}/" \
            f"{meta.get('namespace', '')}/{meta.get('name', '')}"
        with self._lock:
            self._pending.add(uid)

    def enqueue_all(self) -> None:
        with self._lock:
            self._pending.update(e['uid'] for e in self.cache.entries())

    def _pending_rows(self, pending, epoch):
        """Yield ``(uid, resource, hash, digest)`` for each pending uid
        that actually needs work — a generator, so the cache-hit pass
        streams entries one at a time instead of double-materializing a
        1M-entry MetadataCache into parallel row lists before the
        replay/miss partition."""
        for uid in pending:
            entry = self.cache.get(uid)
            if entry is None:
                continue
            prior = self._scanned.get(uid)
            if prior is not None and prior[0] == entry['hash'] and \
                    prior[1] >= epoch:
                continue  # resumability: already scanned this version
            yield (uid, entry['resource'], entry['hash'],
                   entry.get('digest') or spec_digest(entry['resource']))

    def reconcile(self, now: Optional[float] = None) -> List[dict]:
        """Drain the pending set through the verdict-cache filter and
        one batched device scan of the misses, writing
        BackgroundScanReport CRs; unchanged resources scanned after the
        last policy change are skipped.  ``now`` pins the scan
        timestamp (tests use it for bit-identity comparisons)."""
        with self._lock:
            pending = list(self._pending)
            self._pending.clear()
            epoch = self._policy_epoch
        now = time.time() if now is None else now
        from ..observability import provenance, tracing
        from ..observability import device as devtel
        from ..verdictcache import publish_tick
        # decision provenance: every rescan row yields one record —
        # cache_replay (digest, zero device share), batch (dense-scan
        # riders share the tick's device_eval time), or host_fallback
        # (exception-present host sweep)
        prov_on = provenance.enabled()
        # PolicyExceptions are rare and rule-targeted; when any exist
        # the host engine decides (exception semantics:
        # pkg/engine/validation.go:826 hasPolicyExceptions — the
        # compiled path has no exception lanes) and rows are
        # exception-dependent, so the verdict cache stands aside
        exceptions = self._list_exceptions()
        vc = self.verdict_cache \
            if self._verdicts_cacheable and not exceptions else None
        reports: List[dict] = []
        rows = self._pending_rows(pending, epoch)
        try:
            first = next(rows)
        except StopIteration:
            return []
        import itertools
        rows = itertools.chain([first], rows)
        with tracing.start_span('kyverno/rescan', {
                'cache': 'on' if vc is not None else 'off'}) as span:
            if exceptions:
                n_work = 0
                for uid, resource, rhash, digest in rows:
                    n_work += 1
                    t_row = time.monotonic() if prov_on else 0.0
                    report = self._store_report(
                        uid, resource,
                        self._host_scan_row(resource, exceptions),
                        now, rhash)
                    self._scanned[uid] = (rhash, now)
                    if report is not None:
                        reports.append(report)
                    if prov_on:
                        self._record_row(
                            provenance, 'host_fallback', uid, resource,
                            duration_s=time.monotonic() - t_row)
                self._tick_stats(span, publish_tick, n_work,
                                 scanned=n_work, replayed=0)
                return reports
            # verdict-cache filter stage, single streaming pass: hit
            # rows replay (and write their report) the moment they are
            # seen — only the misses (O(churn)) accumulate for the
            # batched device scan
            ts = int(now)
            miss_uids: List[str] = []
            miss_work: List[dict] = []
            miss_digests: List[str] = []
            miss_hashes: List[str] = []
            scoped_uids: List[str] = []
            scoped_work: List[dict] = []
            scoped_digests: List[str] = []
            scoped_hashes: List[str] = []
            scoped_cached: List[dict] = []
            # scoped rescan (partitioned cache, post-churn): a full-row
            # miss whose unchanged partitions all still hold subrows
            # only needs the touched partitions re-evaluated
            scoped_ok = bool(self._scoped_pids) and hasattr(vc, 'partial')
            replayed = 0
            if vc is not None:
                for uid, resource, rhash, digest in rows:
                    row = vc.lookup(digest)
                    if row is None:
                        if scoped_ok:
                            cached = vc.partial(digest, self._scoped_pids)
                            if cached is not None:
                                scoped_uids.append(uid)
                                scoped_work.append(resource)
                                scoped_digests.append(digest)
                                scoped_hashes.append(rhash)
                                scoped_cached.append(cached)
                                continue
                        miss_uids.append(uid)
                        miss_work.append(resource)
                        miss_digests.append(digest)
                        miss_hashes.append(rhash)
                        continue
                    t_row = time.monotonic() if prov_on else 0.0
                    report = self._store_fused_report(
                        uid, resource, vc.replay(row, self.policies, ts),
                        now, rhash)
                    self._scanned[uid] = (rhash, now)
                    if report is not None:
                        reports.append(report)
                    replayed += 1
                    if prov_on:
                        self._record_row(
                            provenance, 'cache_replay', uid, resource,
                            duration_s=time.monotonic() - t_row,
                            verdict_digest=digest)
            else:
                for uid, resource, rhash, digest in rows:
                    miss_uids.append(uid)
                    miss_work.append(resource)
                    miss_digests.append(digest)
                    miss_hashes.append(rhash)
            # scoped rescan: partial-hit rows re-evaluate against ONLY
            # the touched partitions' policies; the unchanged subrows
            # come from the cache and merge_scoped composes + stores
            # the full row (O(touched) device work per row, not O(set))
            if scoped_work:
                scanner = self._get_scoped_scanner()
                cap_s = devtel.ScanCapture()
                t_scoped = time.monotonic()
                with devtel.install_capture(cap_s):
                    for uid, resource, digest, rhash, cached, row in zip(
                            scoped_uids, scoped_work, scoped_digests,
                            scoped_hashes, scoped_cached,
                            scanner.scan_report_results(scoped_work,
                                                        now)):
                        results, summary, row_policies = row
                        m_res, m_sum, m_idx = vc.merge_scoped(
                            digest, uid, cached, results, summary,
                            [self._scoped_globals[id(p)]
                             for p in row_policies], ts)
                        report = self._store_fused_report(
                            uid, resource,
                            (m_res, m_sum,
                             [self.policies[g] for g in m_idx]),
                            now, rhash)
                        self._scanned[uid] = (rhash, now)
                        if report is not None:
                            reports.append(report)
                if prov_on:
                    n_scoped = len(scoped_work)
                    elapsed = time.monotonic() - t_scoped
                    device_eval_s = cap_s.stage_s('device_eval')
                    batch_id = provenance.next_batch_id('rescan-scoped')
                    for uid, resource in zip(scoped_uids, scoped_work):
                        self._record_row(
                            provenance, 'batch', uid, resource,
                            duration_s=elapsed / n_scoped,
                            batch_id=batch_id, occupancy=n_scoped,
                            device_share_s=device_eval_s / n_scoped,
                            device_eval_s=device_eval_s,
                            aot_cache=cap_s.aot,
                            coverage_ratio=cap_s.coverage_ratio)
            # fused fast path over the misses: report results assembled
            # straight from the device cells (bit-identity pinned by
            # tests/test_report_fusion), rows written back to the cache
            if miss_work:
                # the capture feeds both provenance (device-share
                # amortization) and the tick's overlap attribution
                cap = devtel.ScanCapture()
                t_scan = time.monotonic()
                with devtel.install_capture(cap):
                    for uid, resource, digest, rhash, row in zip(
                            miss_uids, miss_work, miss_digests,
                            miss_hashes,
                            self.scanner.scan_report_results(miss_work,
                                                             now)):
                        report = self._store_fused_report(
                            uid, resource, row, now, rhash)
                        self._scanned[uid] = (rhash, now)
                        if report is not None:
                            reports.append(report)
                        if vc is not None:
                            results, summary, row_policies = row
                            vc.store(digest, uid, results, summary,
                                     [self._policy_index[id(p)]
                                      for p in row_policies])
                # per-stage busy time ÷ tick wall: >1 means the
                # pipeline legs genuinely overlapped this tick
                scan_wall = time.monotonic() - t_scan
                if scan_wall > 0:
                    busy = sum(cap.stages.values())
                    span.set_attribute('overlap_ratio',
                                       round(busy / scan_wall, 4))
                if cap.critical_path:
                    from ..observability import timeline as tlmod
                    span.set_attribute(
                        'critical_path',
                        tlmod.format_summary(cap.critical_path))
                if prov_on:
                    # dense-scanned rows are riders of one shared tick
                    # scan: the tick's device_eval time amortizes over
                    # them exactly like an admission batch's riders
                    n_miss = len(miss_work)
                    elapsed = time.monotonic() - t_scan
                    device_eval_s = cap.stage_s('device_eval')
                    batch_id = provenance.next_batch_id('rescan')
                    for uid, resource in zip(miss_uids, miss_work):
                        self._record_row(
                            provenance, 'batch', uid, resource,
                            duration_s=elapsed / n_miss,
                            batch_id=batch_id, occupancy=n_miss,
                            device_share_s=device_eval_s / n_miss,
                            device_eval_s=device_eval_s,
                            aot_cache=cap.aot,
                            coverage_ratio=cap.coverage_ratio)
            self._tick_stats(span, publish_tick,
                             len(miss_work) + len(scoped_work) + replayed,
                             scanned=len(miss_work) + len(scoped_work),
                             replayed=replayed, scoped=len(scoped_work))
        if vc is not None:
            vc.flush()
        return reports

    def _record_row(self, provenance, path: str, uid: str,
                    resource: dict, **fields) -> None:
        """One rescan row's DecisionRecord (resource identity + the
        controller's policy-set fingerprint folded in)."""
        meta = resource.get('metadata') or {}
        provenance.record_decision(
            path=path, source='rescan', uid=uid,
            kind=resource.get('kind', '') or '',
            namespace=meta.get('namespace', '') or '',
            name=meta.get('name', '') or '',
            fingerprint=self._policy_fingerprint, **fields)

    def _tick_stats(self, span, publish_tick, pending: int, scanned: int,
                    replayed: int, scoped: int = 0) -> None:
        self.rescan_stats = {'rows_pending': pending,
                             'rows_scanned': scanned,
                             'rows_replayed': replayed}
        span.set_attribute('rows_scanned', scanned)
        span.set_attribute('rows_replayed', replayed)
        if scoped:
            # only surfaced when a partition-scoped rescan ran, so the
            # steady-state stats dict keeps its legacy three-key shape
            self.rescan_stats['rows_scoped'] = scoped
            span.set_attribute('rows_scoped', scoped)
        publish_tick(scanned, replayed)

    def _store_fused_report(self, uid: str, resource: dict, row,
                            now: float,
                            resource_hash: Optional[str] = None
                            ) -> Optional[dict]:
        from .types import build_fused_report
        results, summary, row_policies = row
        meta = resource.get('metadata') or {}
        ns = meta.get('namespace', '')
        report = build_fused_report(resource, results, summary,
                                    row_policies)
        if not report['metadata'].get('name'):
            report['metadata']['name'] = uid.replace('/', '-').lower()
        set_resource_version_labels(report, resource, resource_hash)
        report['metadata'].setdefault('annotations', {})[
            ANNOTATION_LAST_SCAN_TIME] = _rfc3339(now)
        return self._write_report(report, ns)

    def _write_report(self, report: dict, ns: str) -> Optional[dict]:
        from .results import get_results
        existing = None
        try:
            existing = self.client.get_resource(
                'kyverno.io/v1alpha2', report['kind'], ns,
                report['metadata']['name'])
        except Exception:  # noqa: BLE001
            existing = None
        if not get_results(report):
            # no policy produced a result (e.g. the policy set shrank):
            # an empty report is deleted, not kept around (reference:
            # report/background/controller.go reconcileReport)
            if existing is not None:
                try:
                    self.client.delete_resource(
                        'kyverno.io/v1alpha2', report['kind'], ns,
                        report['metadata']['name'])
                except Exception:  # noqa: BLE001
                    pass
            return None
        if existing is not None:
            existing.update({k: report[k]
                             for k in ('metadata', 'spec', 'results',
                                       'summary') if k in report})
            return self.client.update_resource(
                'kyverno.io/v1alpha2', report['kind'], ns, existing)
        return self.client.create_resource(
            'kyverno.io/v1alpha2', report['kind'], ns, report)

    def _list_exceptions(self) -> List[dict]:
        if self.client is None:
            return []
        out: List[dict] = []
        for api_version in ('kyverno.io/v2alpha1', 'kyverno.io/v2beta1'):
            try:
                out += self.client.list_resource(api_version,
                                                 'PolicyException')
            except Exception:  # noqa: BLE001
                pass
        return out

    def _host_scan_row(self, doc: dict, exceptions: List[dict]):
        from ..engine.api import PolicyContext
        responses = []
        for policy in self.policies:
            pctx = PolicyContext(policy, new_resource=doc,
                                 exceptions=exceptions)
            responses.append(
                self.engine.apply_background_checks(pctx))
        return responses

    def _store_report(self, uid: str, resource: dict, responses,
                      now: float, resource_hash: Optional[str] = None
                      ) -> Optional[dict]:
        meta = resource.get('metadata') or {}
        ns = meta.get('namespace', '')
        report = new_background_scan_report(resource)
        if not report['metadata'].get('name'):
            report['metadata']['name'] = uid.replace('/', '-').lower()
        set_resource_version_labels(report, resource, resource_hash)
        # the scan timestamp annotation drives resumability
        # (reference: controller.go:44 audit.kyverno.io/last-scan-time)
        report.setdefault('metadata', {}).setdefault('annotations', {})[
            ANNOTATION_LAST_SCAN_TIME] = _rfc3339(now)
        relevant = [r for r in responses if r.policy_response.rules]
        set_responses(report, *relevant)
        return self._write_report(report, ns)


class AdmissionReportController:
    """Aggregates per-request AdmissionReports by resource uid and
    deduplicates (reference: report/admission/controller.go:258)."""

    def __init__(self, client):
        self.client = client

    def reconcile(self) -> int:
        """Merge duplicate reports per resource uid; returns merge count."""
        merged = 0
        for kind in ('AdmissionReport', 'ClusterAdmissionReport'):
            try:
                reports = self.client.list_resource(
                    'kyverno.io/v1alpha2', kind, '', None)
            except Exception:  # noqa: BLE001
                continue
            by_uid: Dict[str, List[dict]] = {}
            for report in reports:
                labels = (report.get('metadata') or {}).get('labels') or {}
                uid = labels.get('audit.kyverno.io/resource.uid', '')
                if not uid:
                    continue  # unlabeled reports are not dedup candidates
                by_uid.setdefault(uid, []).append(report)
            for uid, group in by_uid.items():
                group.sort(key=lambda r: (r.get('metadata') or {}).get(
                    'creationTimestamp', ''))
                primary = group[0]
                from .results import (calculate_summary, get_results,
                                      sort_report_results)
                results = list(get_results(primary))
                for extra in group[1:]:
                    results.extend(get_results(extra))
                    ns = (extra.get('metadata') or {}).get('namespace', '')
                    self.client.delete_resource(
                        'kyverno.io/v1alpha2', kind, ns,
                        (extra.get('metadata') or {}).get('name', ''))
                # aggregation stamps the owning resource ref onto every
                # result (reference: report/admission/controller.go:131
                # mergeReports — result.Resources = objectRefs)
                owner_refs = (primary.get('metadata') or {}).get(
                    'ownerReferences') or []
                ns = (primary.get('metadata') or {}).get('namespace', '')
                changed = len(group) > 1
                if len(owner_refs) == 1:
                    owner = owner_refs[0]
                    object_ref = {
                        'apiVersion': owner.get('apiVersion', ''),
                        'kind': owner.get('kind', ''),
                        'name': owner.get('name', ''),
                    }
                    if ns:
                        object_ref['namespace'] = ns
                    if owner.get('uid'):
                        object_ref['uid'] = owner['uid']
                    for result in results:
                        if not result.get('resources'):
                            result['resources'] = [object_ref]
                            changed = True
                if not changed:
                    continue
                sort_report_results(results)
                spec = primary.setdefault('spec', {})
                spec['results'] = results
                spec['summary'] = calculate_summary(results)
                self.client.update_resource(
                    'kyverno.io/v1alpha2', kind, ns, primary)
                merged += 1
        return merged


def _rfc3339(ts: float) -> str:
    import datetime
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc).strftime('%Y-%m-%dT%H:%M:%SZ')
