"""Persistent AOT executable cache + background warm-up subsystem.

Fresh-process warm-up pays 43-88s of jax trace + XLA compile before the
first admission decision (VERDICT #2 / ADVICE r5 #4).  This package is
the compile-once discipline for the whole repo:

* :mod:`.store` — a disk-backed blob store with atomic writes,
  integrity-checked corruption-tolerant loads, and LRU size-capped
  eviction (``KTPU_AOT_CACHE_DIR`` / ``KTPU_AOT_CACHE_MAX``).
* :mod:`.keys` — cache-key derivation covering the policy-set
  fingerprint, jax/jaxlib + XLA environment, device kind/topology, and
  the batch input layout, plus the XLA persistent-compilation-cache
  hookup shared by every jit site.
* :mod:`.warmer` — a background warmer daemons start before first
  traffic: it pre-loads (or pre-compiles) the admission graph for the
  installed policy set and reports readiness through metrics, a span,
  and the webhook health endpoints.

The executable codec itself (jax.experimental.serialize_executable +
compression) lives in :mod:`kyverno_tpu.compiler.aot`, the layer the
jit sites (ops/eval.py, compiler/scan.py, parallel/mesh.py) call.
"""

from .keys import (enable_persistent_compilation_cache,
                   executable_cache_key, policy_set_fingerprint)
from .store import (AOT_CACHE_ENTRIES, AOT_CACHE_SIZE_BYTES, AotStore,
                    default_store, publish_stats, reset_default_store)
from .warmer import AOT_WARM_DURATION, Warmer

__all__ = [
    'AOT_CACHE_ENTRIES', 'AOT_CACHE_SIZE_BYTES', 'AOT_WARM_DURATION',
    'AotStore', 'Warmer', 'default_store', 'reset_default_store',
    'publish_stats', 'enable_persistent_compilation_cache',
    'executable_cache_key', 'policy_set_fingerprint',
]
