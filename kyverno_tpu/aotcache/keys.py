"""Cache-key derivation for persisted executables + the XLA
persistent-compilation-cache hookup.

An AOT entry is only loadable in a process that matches the one that
compiled it, so the key covers every axis that changes the generated
code: the policy-set fingerprint, the evaluator/compiler source digest,
jax + jaxlib versions, the backend platform and device identity
(kind/topology), the host CPU feature set, the ambient XLA environment
(flags, platform selection, which PJRT plugins initialized), and the
batch input signature (name/dtype/shape per lane — the batch layout).
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, Optional, Tuple

import jax

#: bump to invalidate every persisted executable (framing/codec changes)
#: v3: blobs carry compile-time meta (host features / env scope / jax
#: versions) re-checked at load; batch layouts moved to the canonical
#: capacity table (compiler/shapes.py), retiring the pow-2 bucket zoo
AOT_VERSION = 3

_SOURCE_DIGEST: Optional[str] = None


def source_digest() -> str:
    """Digest of the compiler/evaluator sources: any code change
    invalidates AOT entries (the executable bakes in their semantics)."""
    global _SOURCE_DIGEST
    if _SOURCE_DIGEST is None:
        h = hashlib.sha256()
        base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in ('ops/eval.py', 'compiler/compile.py',
                    'compiler/encode.py', 'compiler/ir.py',
                    'compiler/pss_compile.py'):
            try:
                with open(os.path.join(base, rel), 'rb') as f:
                    h.update(f.read())
            except OSError:
                h.update(rel.encode())
        _SOURCE_DIGEST = h.hexdigest()[:16]
    return _SOURCE_DIGEST


def policy_set_fingerprint(policies) -> str:
    """Stable digest of a policy set's raw documents (the evaluator HLO
    is a deterministic function of them — verified cross-process)."""
    import json
    payload = json.dumps([getattr(p, 'raw', p) for p in policies],
                         sort_keys=True, separators=(',', ':'),
                         default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:20]


def host_fingerprint() -> str:
    """Short hash of the host CPU feature set.  XLA:CPU AOT artifacts
    embed the compile machine's features and can SIGILL when loaded on a
    host missing them; scoping the cache dir per feature set keeps a
    shared checkout safe across heterogeneous machines."""
    try:
        with open('/proc/cpuinfo') as f:
            for line in f:
                if line.startswith('flags'):
                    return hashlib.sha256(
                        ' '.join(sorted(line.split())).encode()
                    ).hexdigest()[:10]
    except OSError:
        pass
    import platform
    return hashlib.sha256(platform.machine().encode()).hexdigest()[:10]


def initialized_platforms() -> Tuple[str, ...]:
    """The PJRT platforms live in this process.  An accelerator plugin
    changes XLA:CPU codegen preferences (prefer-no-gather/scatter), so
    CPU executables compiled with a plugin present are not loadable in a
    plugin-free process — cache scopes must separate them."""
    try:
        return tuple(sorted(jax._src.xla_bridge.backends().keys()))
    except Exception:  # noqa: BLE001 - never block caching on this
        try:
            return (jax.default_backend(),)
        except Exception:  # noqa: BLE001
            return ()


def env_scope() -> Tuple:
    """The codegen-relevant process environment: host CPU features plus
    everything that steers XLA's machine-feature preferences."""
    return (host_fingerprint(), os.environ.get('XLA_FLAGS', ''),
            os.environ.get('JAX_PLATFORMS', ''), initialized_platforms())


def executable_cache_key(fingerprint: str, packed: Dict[str, Any],
                         extra: Tuple = ()) -> Optional[str]:
    """Cache key for one (policy set, input signature, platform) combo.

    Returns None when the entry could not be persisted safely:

    * inputs sharded across >1 device (mesh path: executables embed the
      device assignment — not portable);
    * >1 local device on the backend (``deserialize_and_load`` reloads
      executables across ALL local devices, so a 1-device executable
      mis-loads as an N-shard SPMD program — verified on the
      8-virtual-device CPU test env);
    * non-CPU backends (serializing over a remote-TPU tunnel takes
      minutes and starves the host mid-scan; accelerator recompiles
      ride the persistent XLA compilation cache instead).
    """
    try:
        sig = []
        backend = jax.default_backend()
        platform = backend
        for name in sorted(packed):
            v = packed[name]
            sharding = getattr(v, 'sharding', None)
            if sharding is not None:
                devs = getattr(sharding, 'device_set', None)
                if devs is not None:
                    if len(devs) != 1:
                        return None
                    d = next(iter(devs))
                    backend = d.platform
                    # device kind + identity, not just the platform
                    # name: topology/generation changes the executable
                    platform = (f'{d.platform}:{getattr(d, "id", 0)}:'
                                f'{getattr(d, "device_kind", "")}')
            sig.append((name, str(v.dtype), tuple(v.shape)))
        if len(jax.local_devices(backend=backend)) != 1:
            return None
        if backend != 'cpu':
            return None
        payload = repr((AOT_VERSION, source_digest(), jax.__version__,
                        jax.lib.__version__, platform, fingerprint, sig,
                        env_scope(), extra))
        return hashlib.sha256(payload.encode()).hexdigest()[:32]
    except Exception:  # noqa: BLE001 - cache is an optimization only
        return None


# -- XLA persistent compilation cache ---------------------------------------

_PERSISTENT_CACHE_ON = False
_PERSISTENT_CACHE_DIR: Optional[str] = None

#: counted when the feature guard refuses a cache directory (same
#: series the AOT executable store uses for its load rejections)
AOT_LOAD_REJECTED = 'kyverno_tpu_aot_load_rejected_total'

#: marker file recording which host CPU feature set populated a
#: persistent-cache directory
HOSTKEY_FILE = 'HOSTKEY'


def verify_cache_feature_scope(cache_dir: str) -> Tuple[str, bool]:
    """Feature guard for a persistent-XLA-cache directory.

    The default cache dir is already scoped by the env digest, but an
    operator-pinned ``KTPU_COMPILE_CACHE`` shared across heterogeneous
    machines is not — and XLA:CPU entries embed the compile host's CPU
    features, so loading across that boundary risks SIGILL (the
    MULTICHIP dryrun tails).  A ``HOSTKEY`` marker records which
    feature set populated the directory; on mismatch the dir is
    re-scoped to a ``feat-<digest>`` subdirectory and the rejection
    counts on ``kyverno_tpu_aot_load_rejected_total{reason=
    feature_mismatch}``.  Returns ``(usable_dir, rejected)``."""
    fp = host_fingerprint()
    marker = os.path.join(cache_dir, HOSTKEY_FILE)
    recorded: Optional[str] = None
    try:
        with open(marker) as f:
            recorded = f.read().strip()
    except OSError:
        pass
    if recorded is not None and recorded != fp:
        from ..observability.metrics import global_registry
        registry = global_registry()
        if registry is not None:
            registry.inc(AOT_LOAD_REJECTED, reason='feature_mismatch')
        cache_dir = os.path.join(cache_dir, f'feat-{fp}')
        rejected = True
    else:
        rejected = False
    if recorded != fp:
        try:
            os.makedirs(cache_dir, exist_ok=True)
            with open(os.path.join(cache_dir, HOSTKEY_FILE), 'w') as f:
                f.write(fp)
        except OSError:
            pass
    return cache_dir, rejected


def enable_persistent_compilation_cache() -> Optional[str]:
    """Point XLA's persistent compilation cache at a disk directory so a
    fresh process re-serving the same policy set skips the (multi-second)
    backend compile even where AOT executables can't persist (mesh,
    accelerators).  Keyed by XLA on the computation fingerprint, which
    covers the (policy-set, chunk-shape) pair.  Idempotent; returns the
    cache dir (or None when the runtime lacks the knobs)."""
    global _PERSISTENT_CACHE_ON, _PERSISTENT_CACHE_DIR
    if _PERSISTENT_CACHE_ON:
        return _PERSISTENT_CACHE_DIR
    # scope by host CPU features AND the codegen-relevant environment:
    # a TPU-plugin process compiles its CPU executables with different
    # machine-feature preferences (prefer-no-gather/scatter) than a
    # pure-CPU process, and loading across that boundary aborts
    scope = hashlib.sha256(repr(env_scope()).encode()).hexdigest()[:10]
    cache_dir = os.environ.get(
        'KTPU_COMPILE_CACHE',
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), '.cache',
            f'xla-{scope}'))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        # a dir populated by a different CPU feature set (pinned
        # KTPU_COMPILE_CACHE on a shared checkout) is re-scoped, not
        # trusted — its entries could SIGILL this host
        cache_dir, _rejected = verify_cache_feature_scope(cache_dir)
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.5)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        return None
    _PERSISTENT_CACHE_ON = True
    _PERSISTENT_CACHE_DIR = cache_dir
    return cache_dir
