"""Disk-backed AOT blob store: atomic, corruption-tolerant, size-capped.

One entry per cache key (``<key>.aotexe``), written with the
tmp-file + ``os.replace`` protocol so readers never observe a partial
entry, framed with a magic + SHA-256 header so a torn or bit-flipped
entry is detected, deleted, and reported as a miss — a bad entry can
cost a recompile, never a crash or a wrong executable.  Eviction is
LRU by mtime (loads touch their entry) against a byte budget.

Knobs:

* ``KTPU_AOT`` — ``0`` disables the store entirely (default on).
* ``KTPU_AOT_CACHE_DIR`` — cache directory (legacy spelling
  ``KTPU_AOT_CACHE`` still honoured; default ``<repo>/.cache/aot``).
* ``KTPU_AOT_CACHE_MAX`` — byte budget, default 8 GiB.
"""

from __future__ import annotations

import hashlib
import logging
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

_log = logging.getLogger('kyverno.aotcache')

#: entry framing: magic + 32-byte SHA-256 of the payload, then payload
_MAGIC = b'KTAC1\n'
_DIGEST_LEN = 32

_SUFFIX = '.aotexe'
#: pre-subsystem entries (never valid now: different framing + codec)
_LEGACY_SUFFIXES = ('.exe.zst',)

AOT_CACHE_SIZE_BYTES = 'kyverno_tpu_aot_cache_size_bytes'
AOT_CACHE_ENTRIES = 'kyverno_tpu_aot_cache_entries'

_DEFAULT_MAX_BYTES = 8 << 30


def _env_root() -> Optional[str]:
    if os.environ.get('KTPU_AOT', '1') != '1':
        return None
    return (os.environ.get('KTPU_AOT_CACHE_DIR')
            or os.environ.get('KTPU_AOT_CACHE')
            or os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                '.cache', 'aot'))


def _env_max_bytes() -> int:
    try:
        return int(os.environ.get('KTPU_AOT_CACHE_MAX',
                                  str(_DEFAULT_MAX_BYTES)))
    except ValueError:
        return _DEFAULT_MAX_BYTES


class AotStore:
    """One directory of integrity-framed blobs keyed by hex cache key."""

    def __init__(self, root: Optional[str] = None,
                 max_bytes: Optional[int] = None):
        self.max_bytes = _env_max_bytes() if max_bytes is None else max_bytes
        self._lock = threading.Lock()
        if root is None:
            root = _env_root()
        if root is not None:
            try:
                os.makedirs(root, exist_ok=True)
            except OSError:
                root = None
        self.root = root

    @property
    def enabled(self) -> bool:
        return self.root is not None

    def path(self, key: str) -> Optional[str]:
        if self.root is None:
            return None
        return os.path.join(self.root, f'{key}{_SUFFIX}')

    # -- reads ------------------------------------------------------------

    def load(self, key: str) -> Optional[bytes]:
        """The entry's payload, or None (miss).  A short, unframed, or
        digest-mismatched entry is deleted and reported as a miss."""
        path = self.path(key)
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path, 'rb') as f:
                raw = f.read()
        except OSError:
            return None
        header = len(_MAGIC) + _DIGEST_LEN
        payload = raw[header:]
        if (len(raw) < header or not raw.startswith(_MAGIC) or
                hashlib.sha256(payload).digest() !=
                raw[len(_MAGIC):header]):
            _log.warning('aot cache entry %s corrupt; dropping', key[:12])
            self.delete(key)
            return None
        try:
            os.utime(path)  # LRU eviction works off mtime
        except OSError:  # a touch failure must not void a good load
            pass
        return payload

    # -- writes -----------------------------------------------------------

    def put(self, key: str, payload: bytes) -> bool:
        """Atomically persist one entry, evicting LRU entries first so
        the directory stays within the byte budget."""
        path = self.path(key)
        if path is None:
            return False
        framed = _MAGIC + hashlib.sha256(payload).digest() + payload
        try:
            with self._lock:
                self._evict(budget=self.max_bytes - len(framed))
                fd, tmp = tempfile.mkstemp(dir=self.root, suffix='.tmp')
                try:
                    with os.fdopen(fd, 'wb') as f:
                        f.write(framed)
                    os.replace(tmp, path)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise
        except OSError:
            return False
        publish_stats(self)
        return True

    def delete(self, key: str) -> None:
        path = self.path(key)
        if path is None:
            return
        try:
            os.unlink(path)
        except OSError:
            return
        publish_stats(self)

    # -- bookkeeping ------------------------------------------------------

    def _entries(self) -> List[Tuple[float, int, str]]:
        """(mtime, size, path) per live entry; prunes stale tmp files
        and legacy-format entries on the way."""
        out: List[Tuple[float, int, str]] = []
        if self.root is None:
            return out
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            p = os.path.join(self.root, name)
            if name.endswith('.tmp'):
                # orphaned partial writes from killed processes — the
                # atomic-rename protocol never leaves a fresh .tmp
                # behind for long, so stale ones are garbage
                try:
                    if time.time() - os.stat(p).st_mtime > 600:
                        os.unlink(p)
                except OSError:
                    pass
                continue
            if name.endswith(_LEGACY_SUFFIXES):
                try:
                    os.unlink(p)
                except OSError:
                    pass
                continue
            if not name.endswith(_SUFFIX):
                continue
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, p))
        return out

    def _evict(self, budget: int) -> None:
        """Drop oldest entries until the directory fits the budget."""
        entries = sorted(self._entries())
        total = sum(sz for _, sz, _ in entries)
        for _, sz, p in entries:
            if total <= max(budget, 0):
                break
            try:
                os.unlink(p)
                total -= sz
            except OSError:
                pass

    def stats(self) -> Dict[str, int]:
        entries = self._entries()
        return {'entries': len(entries),
                'bytes': sum(sz for _, sz, _ in entries)}


# -- process-global default store -------------------------------------------

_DEFAULT: Optional[AotStore] = None
_DEFAULT_LOCK = threading.Lock()


def default_store() -> AotStore:
    """The env-configured store shared by every jit site.  Stable for
    the process; ``reset_default_store`` re-reads the environment
    (tests flip ``KTPU_AOT_CACHE_DIR`` between cases)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = AotStore()
        return _DEFAULT


def reset_default_store() -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None


def publish_stats(store: Optional[AotStore] = None) -> None:
    """Push the store's entry/byte gauges to the configured registry
    (no-op in unconfigured processes)."""
    from ..observability.metrics import global_registry
    reg = global_registry()
    if reg is None:
        return
    st = (store or default_store()).stats()
    # ktpu: noqa[KTPU603] -- cache bytes describe the on-disk store,
    # which outlives the process; the last sample stays true after a
    # drain, so reset-on-close would be wrong here
    reg.set_gauge(AOT_CACHE_SIZE_BYTES, float(st['bytes']))
    # ktpu: noqa[KTPU603] -- same as above: entry count is persistent
    # store state, not live process occupancy
    reg.set_gauge(AOT_CACHE_ENTRIES, float(st['entries']))
