"""Background warm-up: compile/load the admission graph before traffic.

The daemons (``cmd/internal.Setup`` → admission controller) hand the
warmer a ``warm_fn`` that brings the serving path to readiness — for
admission that means building the ``BatchScanner`` for the installed
enforce policy set, which consults the AOT executable store first and
only falls back to a fresh trace + XLA compile on a cold cache.  The
warmer runs it on a daemon thread, wraps it in a ``kyverno/aot/warmer``
span, times it into ``kyverno_tpu_aot_warm_duration_seconds``, and
publishes the store's size/entry gauges, so "how long until this pod
serves compiled admission" is a dashboard number instead of folklore.

``KTPU_WARM=0`` disables warming entirely: ``start()`` is a no-op, no
thread spawns, and state reads ``disabled`` (requests still serve via
the host engine loop and lazy compilation, exactly as before).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

AOT_WARM_DURATION = 'kyverno_tpu_aot_warm_duration_seconds'

#: warmer lifecycle states
DISABLED = 'disabled'
PENDING = 'pending'
WARMING = 'warming'
READY = 'ready'
FAILED = 'failed'

_log = logging.getLogger('kyverno.aotcache')


def _env_enabled() -> bool:
    return os.environ.get('KTPU_WARM', '1') == '1'


class Warmer:
    """Runs ``warm_fn`` once in the background and reports readiness.

    ``warm_fn`` returns a short human-readable detail string (or None),
    or a ``(detail, attrs)`` pair — ``attrs`` land on the
    ``kyverno/aot/warmer`` span, so a warm pass that loads the canonical
    batch shapes can report exactly how many executables it brought up
    (and from where).  An exception marks the warmer ``failed`` —
    serving is unaffected either way, the un-warmed paths lazily
    compile as before.
    """

    def __init__(self, warm_fn: Callable[[], Optional[str]],
                 name: str = 'admission', registry=None,
                 enabled: Optional[bool] = None):
        self.warm_fn = warm_fn
        self.name = name
        self.registry = registry
        self.enabled = _env_enabled() if enabled is None else enabled
        self.state = PENDING if self.enabled else DISABLED
        self.detail: Optional[str] = None
        self.error: Optional[str] = None
        self.duration_s: Optional[float] = None
        self._done = threading.Event()
        self._started = False
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        if not self.enabled:
            self._done.set()

    @property
    def ready(self) -> bool:
        return self.state == READY

    def start(self) -> bool:
        """Spawn the warm thread; False (and no thread) when disabled.
        Idempotent — later calls return whether a run was ever started."""
        if not self.enabled:
            return False
        with self._lock:
            if self._started:
                return True
            self._started = True
            self._thread = threading.Thread(
                target=self.run_sync, name=f'ktpu-aot-warmer-{self.name}',
                daemon=True)
            self._thread.start()
        return True

    def run_sync(self) -> None:
        """The warm pass itself (the thread body; tests call it inline)."""
        if not self.enabled:
            return
        from ..observability import tracing
        from .store import default_store, publish_stats
        self.state = WARMING
        t0 = time.monotonic()
        with tracing.start_span('kyverno/aot/warmer',
                                {'target': self.name}) as span:
            try:
                detail = self.warm_fn()
                if isinstance(detail, tuple):
                    detail, attrs = detail
                    for k, v in (attrs or {}).items():
                        span.set_attribute(k, v)
                self.detail = detail
                self.state = READY
            except Exception as e:  # noqa: BLE001 - warm failure must
                # never take serving down; the lazy path still compiles
                self.error = str(e)
                self.state = FAILED
            self.duration_s = time.monotonic() - t0
            span.set_attribute('state', self.state)
            span.set_attribute('duration_s', round(self.duration_s, 3))
        reg = self.registry
        if reg is None:
            from ..observability.metrics import global_registry
            reg = global_registry()
        if reg is not None:
            from ..observability.metrics import WIDE_BUCKETS
            reg.register_histogram(AOT_WARM_DURATION, WIDE_BUCKETS)
            reg.observe(AOT_WARM_DURATION, self.duration_s,
                        target=self.name, state=self.state)
        publish_stats(default_store())
        from ..observability.logging import with_values
        with_values(_log, 'aot warm-up finished',
                    level=logging.ERROR if self.state == FAILED
                    else logging.INFO,
                    target=self.name, state=self.state,
                    duration_s=round(self.duration_s, 3),
                    detail=self.detail or self.error or '')
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the warm pass finished (or was disabled)."""
        return self._done.wait(timeout)
