"""Dynamic configuration (reference: pkg/config)."""

from .config import (  # noqa: F401
    KYVERNO_CONFIGMAP_NAME, KYVERNO_NAMESPACE, ConfigController,
    Configuration,
)
