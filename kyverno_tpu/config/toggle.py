"""Feature toggles — the env-var tier of the three-level config system
(reference: pkg/toggle/toggle.go:8-24).

Resolution order matches the reference: an explicitly parsed flag value
wins, then the environment variable, then the default.
"""

from __future__ import annotations

import os
from typing import Optional


class Toggle:
    """reference: toggle.go Toggle interface (Enabled/Parse)."""

    def __init__(self, default: bool, env_var: str):
        self.default = default
        self.env_var = env_var
        self._value: Optional[bool] = None

    def parse(self, value: str) -> None:
        """Flag-tier override (strconv.ParseBool semantics)."""
        v = str(value).strip().lower()
        if v in ('1', 't', 'true'):
            self._value = True
        elif v in ('0', 'f', 'false'):
            self._value = False
        else:
            raise ValueError(f'invalid toggle value {value!r}')

    def enabled(self) -> bool:
        if self._value is not None:
            return self._value
        env = os.environ.get(self.env_var)
        if env is not None:
            v = env.strip().lower()
            if v in ('1', 't', 'true'):
                return True
            if v in ('0', 'f', 'false'):
                return False
        return self.default

    def reset(self) -> None:
        self._value = None


# reference: toggle.go:21-24
PROTECT_MANAGED_RESOURCES = Toggle(False, 'FLAG_PROTECT_MANAGED_RESOURCES')
FORCE_FAILURE_POLICY_IGNORE = Toggle(
    False, 'FLAG_FORCE_FAILURE_POLICY_IGNORE')
