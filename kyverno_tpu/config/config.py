"""Hot-reloadable configuration (reference: pkg/config/config.go).

Loaded from the ``kyverno`` ConfigMap: resource filters, excluded
usernames/group-roles, default registry, webhook namespace selectors,
success-event generation. The config controller re-``load``s on every
ConfigMap change.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional

from ..utils.wildcard import match as wildcard_match

KYVERNO_NAMESPACE = 'kyverno'
KYVERNO_CONFIGMAP_NAME = 'kyverno'

# reference: pkg/config/config.go:34 defaultExcludeGroupRole
DEFAULT_EXCLUDE_GROUP_ROLE = ['system:serviceaccounts:kube-system',
                              'system:nodes', 'system:kube-scheduler']

_FILTER_RE = re.compile(r'\[([^\[\]]*)\]')
_DNS_RE = re.compile(
    r'^([a-zA-Z0-9]([a-zA-Z0-9\-]{0,61}[a-zA-Z0-9])?\.)*'
    r'[a-zA-Z0-9]([a-zA-Z0-9\-]{0,61}[a-zA-Z0-9])?(:[0-9]+)?$')


class _Filter:
    """One [kind,namespace,name] exclusion (reference: config.go filter)."""

    __slots__ = ('kind', 'namespace', 'name')

    def __init__(self, kind: str, namespace: str, name: str):
        self.kind = kind
        self.namespace = namespace
        self.name = name


def _parse_kinds(text: str) -> List[_Filter]:
    """reference: pkg/config/filter.go parseKinds"""
    out = []
    for m in _FILTER_RE.finditer(text or ''):
        elements = [e.strip() for e in m.group(1).split(',')]
        while len(elements) < 3:
            elements.append('')
        kind, namespace, name = elements[0], elements[1], elements[2]
        if not kind:
            continue
        out.append(_Filter(kind or '*', namespace or '*', name or '*'))
    return out


def _parse_rbac(text: str) -> List[str]:
    return [s.strip() for s in (text or '').split(',') if s.strip()]


class Configuration:
    """reference: pkg/config/config.go:133 Configuration"""

    def __init__(self):
        self._lock = threading.RLock()
        self._filters: List[_Filter] = []
        self._default_registry = 'docker.io'
        self._enable_default_registry_mutation = True
        self._exclude_group_role = list(DEFAULT_EXCLUDE_GROUP_ROLE)
        self._exclude_username: List[str] = []
        self._generate_success_events = False
        self._webhooks: List[dict] = []

    # -- reads ---------------------------------------------------------------

    def to_filter(self, kind: str, namespace: str, name: str) -> bool:
        """True when the resource is excluded by resourceFilters
        (reference: config.go:186 ToFilter)."""
        with self._lock:
            for f in self._filters:
                if wildcard_match(f.kind, kind) and \
                        wildcard_match(f.namespace, namespace) and \
                        wildcard_match(f.name, name):
                    return True
            # reference: config.go — kyverno's own namespace is always
            # filtered via the default resourceFilters entry
            return False

    def get_exclude_group_role(self) -> List[str]:
        with self._lock:
            return list(self._exclude_group_role)

    def get_exclude_username(self) -> List[str]:
        with self._lock:
            return list(self._exclude_username)

    def get_default_registry(self) -> str:
        with self._lock:
            return self._default_registry

    def get_enable_default_registry_mutation(self) -> bool:
        with self._lock:
            return self._enable_default_registry_mutation

    def get_generate_success_events(self) -> bool:
        with self._lock:
            return self._generate_success_events

    def get_webhooks(self) -> List[dict]:
        with self._lock:
            return list(self._webhooks)

    # -- load ----------------------------------------------------------------

    def load(self, configmap: Optional[dict]) -> None:
        """reference: config.go:259 load — resets then applies Data."""
        data: Dict[str, str] = ((configmap or {}).get('data') or {})
        with self._lock:
            self._filters = _parse_kinds(data.get('resourceFilters', ''))
            self._exclude_group_role = (
                _parse_rbac(data.get('excludeGroupRole', '')) +
                list(DEFAULT_EXCLUDE_GROUP_ROLE))
            self._exclude_username = _parse_rbac(
                data.get('excludeUsername', ''))
            self._generate_success_events = \
                data.get('generateSuccessEvents', '').lower() == 'true'
            # reset to defaults first so removed/invalid keys revert
            # (reference: pkg/config/config.go load)
            self._default_registry = 'docker.io'
            registry = data.get('defaultRegistry')
            if registry and _DNS_RE.match(registry):
                self._default_registry = registry
            self._enable_default_registry_mutation = True
            mutation = data.get('enableDefaultRegistryMutation')
            if mutation is not None:
                if mutation.lower() in ('true', 'false'):
                    self._enable_default_registry_mutation = \
                        mutation.lower() == 'true'
            webhooks = data.get('webhooks')
            self._webhooks = []
            if webhooks:
                import json
                try:
                    parsed = json.loads(webhooks)
                    if isinstance(parsed, list):
                        self._webhooks = parsed
                except ValueError:
                    pass


class ConfigController:
    """Watches the kyverno ConfigMap in a dclient store and hot-reloads
    the Configuration (reference: pkg/controllers/config/controller.go)."""

    def __init__(self, client, configuration: Configuration):
        self.client = client
        self.configuration = configuration
        client.watch(self._on_event)
        self.reconcile()

    def reconcile(self) -> None:
        from ..dclient.client import NotFoundError
        try:
            cm = self.client.get_resource(
                'v1', 'ConfigMap', KYVERNO_NAMESPACE, KYVERNO_CONFIGMAP_NAME)
        except NotFoundError:
            cm = None
        self.configuration.load(cm)

    def _on_event(self, event: str, resource: dict) -> None:
        meta = resource.get('metadata') or {}
        if resource.get('kind') == 'ConfigMap' and \
                meta.get('name') == KYVERNO_CONFIGMAP_NAME and \
                meta.get('namespace') == KYVERNO_NAMESPACE:
            if event == 'DELETED':
                self.configuration.load(None)
            else:
                self.configuration.load(resource)
