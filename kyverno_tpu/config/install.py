"""Install-time cluster objects the chart ships (reference:
charts/kyverno/templates/rbac/aggregated-roles — the aggregated
ClusterRoles that surface kyverno CRs to the built-in admin/view roles,
asserted by test/conformance/kuttl/rbac/aggregate-to-admin).

The daemons assume these exist the way the reference assumes its Helm
install ran; ``seed_install_manifests`` creates them idempotently.
"""

from __future__ import annotations

_CRUD_VERBS = ['create', 'delete', 'get', 'list', 'patch', 'update',
               'watch']

_AGGREGATED_ADMIN_ROLES = [
    ('kyverno:admin:policies', 'kyverno.io',
     ['cleanuppolicies', 'clustercleanuppolicies', 'policies',
      'clusterpolicies']),
    ('kyverno:admin:policyreports', 'wgpolicyk8s.io',
     ['policyreports', 'clusterpolicyreports']),
    ('kyverno:admin:reports', 'kyverno.io',
     ['admissionreports', 'clusteradmissionreports',
      'backgroundscanreports', 'clusterbackgroundscanreports']),
    ('kyverno:admin:updaterequests', 'kyverno.io',
     ['updaterequests']),
]


def install_cluster_roles() -> list:
    """The aggregated admin ClusterRoles as unstructured docs."""
    docs = []
    for name, group, resources in _AGGREGATED_ADMIN_ROLES:
        docs.append({
            'apiVersion': 'rbac.authorization.k8s.io/v1',
            'kind': 'ClusterRole',
            'metadata': {
                'name': name,
                'labels': {
                    'rbac.authorization.k8s.io/aggregate-to-admin': 'true',
                },
            },
            'rules': [{
                'apiGroups': [group],
                'resources': list(resources),
                'verbs': list(_CRUD_VERBS),
            }],
        })
    return docs


def seed_install_manifests(client) -> None:
    """Create the install-time objects in ``client`` (idempotent)."""
    from ..dclient.client import ApiError
    for doc in install_cluster_roles():
        try:
            client.create_resource(doc['apiVersion'], doc['kind'], '', doc)
        except ApiError:
            pass
