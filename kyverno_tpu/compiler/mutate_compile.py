"""Precompiled appliers for the common bulk-mutation shapes.

The engine's generic mutate loop re-substitutes and re-walks the rule
tree per (resource, element) — correct, but 10-20x more host work than
the mutation itself on dump-scale applies (BASELINE config 5).  This
module compiles the three dominant shapes into direct appliers:

* static ``patchStrategicMerge`` overlays of nested dicts with scalar
  leaves and ``+(key)`` add-if-absent anchors
* static ``patchesJson6902`` add/replace ops on object paths
* single-entry ``foreach`` over a resource list with simple per-element
  preconditions and a merge-by-name strategic overlay whose only
  variable is the ``{{element.name}}`` self-reference

Everything else returns ``None`` and the caller keeps the engine loop.
Appliers may also return :data:`FALLBACK` per resource when the live
document's shape leaves the compiled fast path (e.g. a non-dict where
the overlay expects a map) — the caller re-runs that resource through
the engine, so results are bit-identical by construction
(tests/test_mutate_compile.py pins equality on randomized docs;
reference semantics: pkg/engine/mutate/patch/strategicMergePatch.go,
patchJSON6902.go, mutation.go ForEach).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..engine import operators
from ..engine.api import RuleStatus
from ..engine.jmespath import compile as jp_compile
from ..engine.mutate.mutate import _success_message
from ..engine.variables import RE_VARIABLE_INIT, tree_has_variables
from ..observability import coverage
from ..observability.coverage import (REASON_DUP_ELEMENT_NAMES,
                                      REASON_NON_DICT,
                                      REASON_PRECONDITION_ESCAPE,
                                      REASON_REPLACE_PATH_MISSING)

#: sentinel: this resource's shape left the compiled fast path
FALLBACK = object()


def _fallback(reason: str, rule_name: str = '', policy_name: str = ''):
    """Record one attributed fast-path escape on the coverage ledger
    (``kyverno_tpu_host_fallback_total{path="mutate", reason=...}``; a
    no-op until coverage.configure) and return the shared FALLBACK
    sentinel — callers and tests compare by identity."""
    coverage.record_fallback('mutate', reason, policy=policy_name,
                             rule=rule_name)
    return FALLBACK

_ADD_ANCHOR_RE = re.compile(r'^\+\((.+)\)$')


def _static(node) -> bool:
    """No {{...}} variables / $() references anywhere in the tree —
    the engine's own predicate, shared so the fast-mutate compiler can
    never drift from substitution semantics."""
    return not tree_has_variables(node)


class CompiledMutation:
    """One rule's fast applier: ``apply(doc) -> (status, message,
    changed, patched) | FALLBACK``."""

    __slots__ = ('apply',)

    def __init__(self, apply_fn):
        self.apply = apply_fn


# -- static strategic merge (dict paths) ------------------------------------

def _compile_overlay(overlay: Any) -> Optional[List[Tuple[Tuple[str, ...],
                                                          bool, Any]]]:
    """Flatten a static dict overlay into (path, add_only, value) sets;
    None when the shape is outside the fast vocabulary."""
    if not isinstance(overlay, dict) or not _static(overlay):
        return None
    out: List[Tuple[Tuple[str, ...], bool, Any]] = []

    def walk(node: dict, path: Tuple[str, ...]) -> bool:
        for key, value in node.items():
            if not isinstance(key, str):
                return False
            add_only = False
            m = _ADD_ANCHOR_RE.match(key)
            if m:
                add_only = True
                key = m.group(1)
            elif '(' in key or ')' in key:
                return False  # conditional/equality/global anchors
            if isinstance(value, dict):
                if add_only:
                    return False  # +() on maps: engine semantics differ
                if not walk(value, path + (key,)):
                    return False
            elif isinstance(value, (list,)):
                return False
            else:
                out.append((path + (key,), add_only, value))
        return True

    if not walk(overlay, ()):
        return None
    return out


def _apply_sets(doc: dict, sets: List[Tuple[Tuple[str, ...], bool, Any]],
                rule_name: str = '', policy_name: str = ''):
    """Copy-on-write application of flattened scalar sets; returns
    (changed, patched) or FALLBACK on a non-dict intermediate.  Every
    escape is attributed on the coverage ledger at its decision site —
    the three returns below each name their reason via ``_fallback`` —
    so callers propagate the sentinel without re-recording."""
    changes = []
    for path, add_only, value in sets:
        cur: Any = doc
        for part in path[:-1]:
            if not isinstance(cur, dict):
                # the overlay path descends through a non-map value
                return _fallback(REASON_NON_DICT, rule_name, policy_name)
            cur = cur.get(part)
            if cur is None:
                break
        leaf = path[-1]
        if cur is None:
            # missing intermediate maps: the merge creates the path
            changes.append((path, value))
            continue
        if not isinstance(cur, dict):
            # the leaf's parent container is a non-map value
            return _fallback(REASON_NON_DICT, rule_name, policy_name)
        if leaf in cur:
            if not add_only and cur[leaf] != value:
                changes.append((path, value))
        else:
            changes.append((path, value))
    if not changes:
        return False, doc
    patched = apply_edit_list(doc, changes)
    if patched is None:
        # copy-on-write hit a non-map while rebuilding the path
        return _fallback(REASON_NON_DICT, rule_name, policy_name)
    return True, patched


def apply_edit_list(doc: dict,
                    changes: List[Tuple[Tuple[str, ...], Any]]):
    """Copy-on-write application of a DECIDED (path, value) edit list —
    the patch phase shared by ``_apply_sets`` and the device-mutate
    decode (``kyverno_tpu/mutate/scanner.py``, which reads the edit
    bitmask back from the device and materializes it here).  Returns
    the patched document, or None when a non-map parent appears while
    rebuilding a path (callers attribute the escape)."""
    if not changes:
        return doc
    patched = dict(doc)
    copied: Dict[Tuple[str, ...], dict] = {(): patched}

    def cow(path: Tuple[str, ...]) -> Any:
        node = copied.get(path)
        if node is not None:
            return node
        parent = cow(path[:-1])
        if not isinstance(parent, dict):
            return None
        child = parent.get(path[-1])
        child = dict(child) if isinstance(child, dict) else {}
        parent[path[-1]] = child
        copied[path] = child
        return child

    for path, value in changes:
        parent = cow(path[:-1])
        if parent is None:
            return None
        parent[path[-1]] = value
    return patched


def compile_strategic_merge(overlay: Any, rule_name: str = '',
                            policy_name: str = ''
                            ) -> Optional[CompiledMutation]:
    sets = _compile_overlay(overlay)
    if sets is None:
        return None

    def apply(doc: dict):
        result = _apply_sets(doc, sets, rule_name, policy_name)
        if result is FALLBACK:
            return result  # attributed at the _apply_sets decision site
        changed, patched = result
        if not changed:
            return (RuleStatus.SKIP, 'no patches applied', False, doc)
        return (RuleStatus.PASS, _success_message(patched), True, patched)

    return CompiledMutation(apply)


# -- static json6902 --------------------------------------------------------

def parse_json6902_sets(patch_text: Any):
    """``(sets, replace_paths)`` for a static add/replace object-path
    json6902 patch, or None when the shape leaves the fast vocabulary
    (array indexes, other ops, variables, unparseable text).  Shared by
    :func:`compile_json6902` and the device-mutate lowering
    (``kyverno_tpu/mutate/plan.py``) so the two paths can never accept
    different patch grammars."""
    from ..engine.mutate.mutate import _load_patches_cached
    if not isinstance(patch_text, str) or '{{' in patch_text:
        return None
    try:
        ops = _load_patches_cached(patch_text)
    except Exception:  # noqa: BLE001 - engine reports the parse error
        return None
    sets: List[Tuple[Tuple[str, ...], bool, Any]] = []
    replace_paths: List[Tuple[str, ...]] = []
    for op in ops:
        op_name = (op or {}).get('op')
        if op_name not in ('add', 'replace'):
            return None
        path = str(op.get('path', ''))
        parts = tuple(p.replace('~1', '/').replace('~0', '~')
                      for p in path.split('/') if p)
        if not parts or any(p.isdigit() or p == '-' for p in parts):
            return None  # array-index ops keep the engine path
        if not _static(op.get('value')):
            return None
        if op_name == 'replace':
            replace_paths.append(parts)
        sets.append((parts, False, op.get('value')))
    return sets, replace_paths


def compile_json6902(patch_text: Any, rule_name: str = '',
                     policy_name: str = '') -> Optional[CompiledMutation]:
    parsed = parse_json6902_sets(patch_text)
    if parsed is None:
        return None
    sets, replace_paths = parsed

    def apply(doc: dict):
        # `replace` requires the leaf AND every intermediate to exist —
        # the engine FAILs with "replace path not found"; only `add`
        # may create paths.  FALLBACK re-runs the engine for the exact
        # failure response.
        for parts in replace_paths:
            cur: Any = doc
            for part in parts:
                if not isinstance(cur, dict) or part not in cur:
                    return _fallback(REASON_REPLACE_PATH_MISSING,
                                     rule_name, policy_name)
                cur = cur[part]
        result = _apply_sets(doc, sets, rule_name, policy_name)
        if result is FALLBACK:
            return result  # attributed at the _apply_sets decision site
        changed, patched = result
        if not changed:
            return (RuleStatus.SKIP, 'no patches applied', False, doc)
        return (RuleStatus.PASS, _success_message(patched), True, patched)

    return CompiledMutation(apply)


# -- foreach ----------------------------------------------------------------

def _compile_element_conditions(conditions: Any) -> Optional[Callable]:
    """Per-element precondition evaluator for conditions whose keys are
    single {{element...}} JMESPath expressions and values are static."""
    if conditions is None:
        return lambda element: True
    blocks: List[Tuple[str, list]] = []
    if isinstance(conditions, dict):
        for mode in ('all', 'any'):
            if conditions.get(mode) is not None:
                blocks.append((mode, conditions[mode]))
    elif isinstance(conditions, list):
        blocks.append(('all', conditions))
    else:
        return None
    compiled_blocks = []
    for mode, conds in blocks:
        compiled = []
        for cond in conds or []:
            if not isinstance(cond, dict):
                return None
            key = cond.get('key')
            if not isinstance(key, str):
                return None
            stripped = key.strip()
            m = RE_VARIABLE_INIT.match(stripped)
            if not m or m.group(0) != stripped:
                return None  # key must be exactly one {{...}} variable
            expr = stripped[2:-2].strip()
            if 'element' not in expr:
                return None
            value = cond.get('value')
            if not _static(value) or not _static(cond.get('operator', '')):
                return None
            try:
                searcher = jp_compile(expr)
            except Exception:  # noqa: BLE001
                return None
            compiled.append((searcher, str(cond.get('operator', '')),
                             value))
        compiled_blocks.append((mode, compiled))

    def evaluate(element: Any) -> Optional[bool]:
        ctx = {'element': element}
        for mode, compiled in compiled_blocks:
            outcomes = []
            for searcher, op, value in compiled:
                try:
                    key_val = searcher.search(ctx)
                except Exception:  # noqa: BLE001 - engine decides
                    return None
                if key_val is None:
                    # the engine surfaces unresolved keys as substitution
                    # errors; anything null-ish leaves the fast path
                    return None
                outcomes.append(operators.evaluate(
                    None, {'key': key_val, 'operator': op,
                           'value': value}))
            if mode == 'all' and not all(outcomes):
                return False
            if mode == 'any' and outcomes and not any(outcomes):
                return False
        return True

    return evaluate


def compile_foreach(foreach_list: Any, rule: dict,
                    policy_name: str = '') -> Optional[CompiledMutation]:
    """Single-entry foreach over a list of named maps with an inner
    merge-by-name overlay (the imagePullPolicy shape)."""
    rule_name = str(rule.get('name', ''))
    if rule.get('preconditions') is not None or \
            not isinstance(foreach_list, list) or len(foreach_list) != 1:
        return None
    entry = foreach_list[0] or {}
    if entry.get('context') or entry.get('foreach') is not None or \
            entry.get('patchesJson6902') is not None:
        return None
    list_expr = entry.get('list', '')
    if not isinstance(list_expr, str) or '{{' in list_expr:
        return None
    if not list_expr.startswith('request.object.'):
        return None
    list_path = tuple(list_expr[len('request.object.'):].split('.'))
    cond_eval = _compile_element_conditions(entry.get('preconditions'))
    if cond_eval is None:
        return None
    overlay = entry.get('patchStrategicMerge')
    # expected shape: the list path mirrored with ONE element dict whose
    # merge key is name: "{{element.name}}" and static scalar sets
    node = overlay
    for part in list_path:
        if not isinstance(node, dict) or set(node) - {part}:
            return None
        node = node.get(part)
    if not isinstance(node, list) or len(node) != 1 or \
            not isinstance(node[0], dict):
        return None
    elem_overlay = dict(node[0])
    name_ref = elem_overlay.pop('name', None)
    if not isinstance(name_ref, str) or \
            name_ref.replace(' ', '') != '{{element.name}}':
        return None
    elem_sets = _compile_overlay(elem_overlay)
    if elem_sets is None:
        return None

    def apply(doc: dict):
        cur: Any = doc
        for part in list_path:
            if not isinstance(cur, dict):
                return _fallback(REASON_NON_DICT, rule_name, policy_name)
            cur = cur.get(part)
        if not isinstance(cur, list) or \
                not all(isinstance(e, dict) for e in cur):
            return _fallback(REASON_NON_DICT, rule_name, policy_name)
        # the engine's strategic merge matches overlay entries to list
        # elements BY NAME and coalesces duplicates onto the first
        # occurrence; the fast path patches elements independently, so
        # duplicate (or non-string) names must take the engine path
        names = [e.get('name') for e in cur]
        if any(not isinstance(n, str) for n in names) or \
                len(set(names)) != len(names):
            return _fallback(REASON_DUP_ELEMENT_NAMES, rule_name,
                             policy_name)
        new_list = None
        for i, element in enumerate(cur):
            passed = cond_eval(element)
            if passed is None:
                return _fallback(REASON_PRECONDITION_ESCAPE, rule_name,
                                 policy_name)
            if not passed:
                continue
            result = _apply_sets(element, elem_sets, rule_name, policy_name)
            if result is FALLBACK:
                return result  # attributed at the _apply_sets decision site
            changed, patched_elem = result
            if changed:
                if new_list is None:
                    new_list = list(cur)
                new_list[i] = patched_elem
        if new_list is None:
            # the engine's foreach reports PASS per processed entry even
            # without patches (mutation.go ForEach apply_count)
            return (RuleStatus.PASS, _success_message(doc), False, doc)
        patched = dict(doc)
        node: Any = patched
        for part in list_path[:-1]:
            child = dict(node[part])
            node[part] = child
            node = child
        node[list_path[-1]] = new_list
        return (RuleStatus.PASS, _success_message(patched), True, patched)

    return CompiledMutation(apply)


def compile_mutate_rule(rule: dict,
                        policy_name: str = '') -> Optional[CompiledMutation]:
    """Fast applier for one mutate rule, or None → engine loop.
    ``policy_name`` labels the applier's runtime FALLBACK attribution
    on the coverage ledger."""
    if rule.get('context') or rule.get('preconditions') is not None:
        return None
    mutation = rule.get('mutate') or {}
    if mutation.get('targets'):
        return None
    rule_name = str(rule.get('name', ''))
    if mutation.get('foreach') is not None:
        return compile_foreach(mutation['foreach'], rule, policy_name)
    if mutation.get('patchStrategicMerge') is not None:
        if mutation.get('patchesJson6902'):
            return None
        return compile_strategic_merge(mutation['patchStrategicMerge'],
                                       rule_name, policy_name)
    if mutation.get('patchesJson6902'):
        return compile_json6902(mutation['patchesJson6902'], rule_name,
                                policy_name)
    return None
