"""Bulk mutate + generate over a resource dump (BASELINE config 5).

The reference applies mutate/generate policies one admission request or
one UpdateRequest at a time (reference: pkg/engine/mutation.go rule
loop, pkg/background/generate/generate.go).  A dump-scale apply
(millions of resources) is a batch problem: the per-rule *match*
decision is group/label-cacheable exactly like the validate scan
(compiler/scan.py match_matrix), and the per-hit mutation work is
embarrassingly parallel across resources.  ``BatchApplier`` does the
cached match sieve first, then fans the matched (resource × policy)
work over a process pool — each worker holds its own Engine, results
are bit-identical to the serial engine loop.

Generate rules don't mutate the trigger; they emit the same UpdateRequest
specs the webhook hands to the background controller
(reference: pkg/webhooks/resource/updaterequest.go:20), so a dump apply
feeds the identical UR pipeline.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from ..api.policy import Policy
from ..api.unstructured import Resource
from ..engine.api import PolicyContext
from ..engine.engine import Engine
from ..engine.match import matches_resource_description
from ..observability import coverage
from .scan import _group_key, _rule_match_is_label_simple, \
    _rule_match_is_simple, policy_namespace_gate


def mutate_placements(policies: List[Policy]) -> list:
    """Per-(policy, rule) placement of the bulk-apply path, mirroring
    BatchApplier's fast-path qualification: ``device`` = precompiled
    fast applier (mutate_compile), ``host`` = engine loop, with the
    attributed reason.  Generate rules are host-bound by design (they
    emit UpdateRequest specs through the background pipeline)."""
    import os as _os
    from .mutate_compile import compile_mutate_rule
    fast_enabled = _os.environ.get('KTPU_FAST_MUTATE', '1') == '1'
    out = []
    for i, p in enumerate(policies):
        mutate_rules = [r for r in p.rules if r.has_mutate()]
        compiled = {r.name: fast_enabled and
                    compile_mutate_rule(r.raw, p.name) is not None
                    for r in mutate_rules}
        policy_ok = fast_enabled and bool(mutate_rules) and \
            all(compiled.values()) and \
            (p.apply_rules or 'All') != 'One' and not p.is_namespaced
        for r in mutate_rules:
            if policy_ok:
                out.append(coverage.RulePlacement(
                    p.name, r.name, 'mutate',
                    coverage.PLACEMENT_DEVICE, None, '', i))
            elif compiled.get(r.name):
                out.append(coverage.RulePlacement(
                    p.name, r.name, 'mutate', coverage.PLACEMENT_HOST,
                    coverage.REASON_POLICY_COUPLING,
                    'rule compiled but the policy leaves the fast path '
                    '(sibling rule, applyRules=One, or namespaced)', i))
            else:
                out.append(coverage.RulePlacement(
                    p.name, r.name, 'mutate', coverage.PLACEMENT_HOST,
                    coverage.REASON_UNSUPPORTED_OPERATOR,
                    'mutation shape outside the fast-applier '
                    'vocabulary (mutate_compile.py)', i))
        for r in p.rules:
            if r.has_generate():
                out.append(coverage.RulePlacement(
                    p.name, r.name, 'generate', coverage.PLACEMENT_HOST,
                    coverage.REASON_HOST_CLOSURE,
                    'generate rules feed the UpdateRequest pipeline', i))
    return out


class ApplyResult:
    """Per-resource outcome of a bulk apply.

    ``rule_results`` is a compact [(policy, rule, status, message), ...]
    list — identical whether the apply ran in-process or on the pool
    (EngineResponse objects don't cross process boundaries cheaply)."""

    __slots__ = ('patched', 'rule_results', 'ur_specs')

    def __init__(self, patched: dict, rule_results: list, ur_specs: list):
        self.patched = patched        # resource after cumulative mutation
        self.rule_results = rule_results
        self.ur_specs = ur_specs      # UpdateRequest specs (generate)


def _ur_spec(policy: Policy, doc: dict) -> dict:
    r = Resource(doc)
    return {
        'requestType': 'generate',
        'policy': policy.name,
        'resource': {'kind': r.kind, 'apiVersion': r.api_version,
                     'namespace': r.namespace, 'name': r.name},
        'context': {'userInfo': {},
                    'admissionRequestInfo': {'operation': 'CREATE'}},
    }


class BatchApplier:
    """Compiles the match sieve once; applies mutate+generate to dumps.

    Mutation chains cumulatively per resource in policy order — the
    patched output of one policy is the next policy's input, matching
    the webhook's sequential mutate handler
    (reference: pkg/webhooks/resource/handlers.go Mutate loop).
    """

    def __init__(self, policies: List[Policy],
                 engine: Optional[Engine] = None,
                 processes: Optional[int] = None):
        self.engine = engine or Engine()
        self.mutate_policies = [p for p in policies
                                if any(r.has_mutate() for r in p.rules)]
        self.generate_policies = [p for p in policies
                                  if any(r.has_generate() for r in p.rules)]
        self.policies = self.mutate_policies + self.generate_policies
        # one match column per (policy, rule); a policy applies when any
        # of its rules match
        self._cols: List[Tuple[int, object]] = []  # (policy idx, Rule)
        for pi, p in enumerate(self.policies):
            for rule in p.rules:
                self._cols.append((pi, rule))
        self._simple = [_rule_match_is_simple(c[1].raw) for c in self._cols]
        self._label = [(not s) and _rule_match_is_label_simple(c[1].raw)
                       for s, c in zip(self._simple, self._cols)]
        self._match_cache: Dict[Tuple, tuple] = {}
        if processes is None:
            processes = 0 if len(self.policies) == 0 else \
                min(os.cpu_count() or 1,
                    int(os.environ.get('KTPU_APPLY_PROCS', '8')))
        self.processes = processes
        # precompiled fast appliers: a mutate policy qualifies when EVERY
        # mutate rule compiles (mutate_compile.py); per-resource shape
        # escapes fall back to the engine, so results stay bit-identical
        from .mutate_compile import compile_mutate_rule
        self._fast_mutate: Dict[int, list] = {}
        if os.environ.get('KTPU_FAST_MUTATE', '1') == '1':
            for pi, p in enumerate(self.mutate_policies):
                compiled = []
                ok = True
                for rule in p.rules:
                    if not rule.has_mutate():
                        continue
                    fast = compile_mutate_rule(rule.raw, p.name)
                    if fast is None:
                        ok = False
                        break
                    compiled.append((rule, fast))
                if ok and compiled and (p.apply_rules or 'All') != 'One' \
                        and not p.is_namespaced:
                    self._fast_mutate[pi] = compiled
        if coverage.enabled():
            # mutate/generate half of the coverage ledger (runtime
            # FALLBACK escapes are attributed inside the appliers; note
            # that process-pool applies count in the worker, so bulk
            # parallel runs under-report on the parent's ledger)
            coverage.record_placements(mutate_placements(self.policies))

    # -- match sieve --------------------------------------------------------

    def _match_col(self, col: int, res: Resource) -> bool:
        pi, rule = self._cols[col]
        if not policy_namespace_gate(self.policies[pi], res):
            return False
        return matches_resource_description(
            res, rule, None, [], {}, '') is None

    def matched_policies(self, doc: dict) -> List[int]:
        """Indices into self.policies whose rules match ``doc``; simple
        and label-simple columns are cached by group / (group, labels)."""
        res = Resource(doc)
        gkey = _group_key(doc)
        cached = self._match_cache.get(gkey)
        if cached is None:
            cached = tuple(self._match_col(c, res) if self._simple[c]
                           else False for c in range(len(self._cols)))
            self._match_cache[gkey] = cached
        cols = list(cached)
        if any(self._label):
            labels = (doc.get('metadata') or {}).get('labels') or {}
            lkey = (gkey, tuple(sorted(labels.items())))
            lcached = self._match_cache.get(lkey)
            if lcached is None:
                lcached = tuple(self._match_col(c, res)
                                for c in range(len(self._cols))
                                if self._label[c])
                self._match_cache[lkey] = lcached
            it = iter(lcached)
            for c in range(len(self._cols)):
                if self._label[c]:
                    cols[c] = next(it)
        for c in range(len(self._cols)):
            if not self._simple[c] and not self._label[c]:
                cols[c] = self._match_col(c, res)
        return sorted({self._cols[c][0] for c, hit in enumerate(cols)
                       if hit})

    # -- application --------------------------------------------------------

    def _apply_one(self, doc: dict) -> ApplyResult:
        hits = self.matched_policies(doc)
        patched = doc
        rule_results = []
        ur_specs = []
        n_mut = len(self.mutate_policies)
        for pi in hits:
            policy = self.policies[pi]
            if pi < n_mut:
                fast = self._fast_mutate.get(pi)
                if fast is not None:
                    out = self._apply_fast(policy, fast, patched)
                    if out is not None:
                        results, patched = out
                        rule_results.extend(results)
                        continue
                ctx = PolicyContext(policy, new_resource=patched)
                resp = self.engine.mutate(ctx)
                rule_results.extend(
                    (policy.name, rr.name, str(rr.status), rr.message)
                    for rr in resp.policy_response.rules)
                if resp.patched_resource is not None:
                    patched = resp.patched_resource
            else:
                ur_specs.append(_ur_spec(policy, patched))
        return ApplyResult(patched, rule_results, ur_specs)

    def _apply_fast(self, policy: Policy, compiled, doc: dict):
        """Run a policy's precompiled mutate appliers; None → the doc's
        shape needs the engine loop (bit-identical fallback)."""
        from .mutate_compile import FALLBACK
        results = []
        patched = doc
        res = Resource(doc)
        for rule, fast in compiled:
            if matches_resource_description(
                    res, rule, None, [], {}, '') is not None:
                continue
            out = fast.apply(patched)
            if out is FALLBACK:
                return None
            status, message, changed, new_doc = out
            results.append((policy.name, rule.name, str(status), message))
            if changed:
                patched = new_doc
                res = Resource(patched)
        return results, patched

    def apply(self, resources: List[dict],
              parallel: Optional[bool] = None) -> List[ApplyResult]:
        """Apply the pack to every resource; order-preserving.

        ``parallel=None`` auto-selects: dumps above ~2k resources fan
        out over the process pool, small batches stay in-process."""
        if parallel is None:
            parallel = self.processes > 1 and len(resources) >= 2048
        if not parallel:
            return [self._apply_one(doc) for doc in resources]
        return self._apply_parallel(resources)

    def _pool_executor(self):
        """Lazily created, reused process pool (worker startup rebuilds
        the engine per process — paying that per apply() call would
        dominate small dumps)."""
        if getattr(self, '_pool', None) is None:
            import weakref
            from concurrent.futures import ProcessPoolExecutor
            pool = ProcessPoolExecutor(
                max_workers=self.processes,
                initializer=_worker_init,
                initargs=([p.raw for p in self.policies],))
            self._pool = pool
            self._pool_finalizer = weakref.finalize(
                self, pool.shutdown, wait=False, cancel_futures=True)
        return self._pool

    def _apply_parallel(self, resources: List[dict]) -> List[ApplyResult]:
        chunk = max(256, len(resources) // (self.processes * 4))
        parts = [resources[i:i + chunk]
                 for i in range(0, len(resources), chunk)]
        try:
            outs = list(self._pool_executor().map(_worker_apply, parts))
        except Exception:  # noqa: BLE001 - pool loss degrades in-process
            # shut the workers down before dropping the reference — the
            # failure may be a bad input rather than pool death, and
            # orphaned workers would stack up across incidents
            fin = getattr(self, '_pool_finalizer', None)
            if fin is not None:
                fin()
            self._pool = None
            return [self._apply_one(doc) for doc in resources]
        results: List[ApplyResult] = []
        for part in outs:
            for patched, rule_results, urs in part:
                results.append(ApplyResult(patched, rule_results, urs))
        return results


# -- process-pool workers (module-level for pickling) -----------------------

_WORKER_APPLIER: Optional[BatchApplier] = None


def _worker_init(policy_docs: List[dict]) -> None:
    global _WORKER_APPLIER
    _WORKER_APPLIER = BatchApplier([Policy(d) for d in policy_docs],
                                   processes=0)


def _worker_apply(docs: List[dict]):
    return [(r.patched, r.rule_results, r.ur_specs)
            for r in map(_WORKER_APPLIER._apply_one, docs)]
