"""Batch encoder v2: resources → fixed-shape slot + gather tensors.

Projects each resource onto the compiled slot table and evaluates gather
expressions with the in-repo JMESPath interpreter (the document itself
never reaches the device).  Encoding is conservative toward UNKNOWN: any
value the encoder cannot represent exactly sets flags that make the
device evaluator emit STATUS_HOST, after which the host engine re-runs
that (resource, rule) pair — correctness is never lost.

Lane schema (shared by slots and gather elements; shapes are [R],
[R, E], [R, E, E2] for slots by star-depth, [R, G] for gathers):
  tag        i8   type tag (ir.TAG_*)
  milli      i64  numeric value ×1000 (ints exact; quantity strings)
  milli_ok   bool milli lane is exact
  nanos      i64  Go duration in ns (strings with units)
  nanos_ok   bool
  str_is_int / str_is_float / str_is_qty / str_is_dur   bool
  has_wild   bool value's string form contains * or ? (gathers only)
  str_len    i32  byte length of the value's string form
  str_head   u8[STR_LEN]  first bytes
  str_tail   u8[TAIL_LEN] last bytes, right-aligned
Array nodes referenced by forall/exists additionally get, keyed by path:
  count      i32  number of elements (clamped to MAX_ELEMS)
  overflow   bool more than MAX_ELEMS elements → device UNKNOWN
Gathers additionally get:
  kind       i8   0 = null/absent, 1 = scalar, 2 = list
  count      i32
  overflow   bool
"""

from __future__ import annotations

import math
from fractions import Fraction
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.duration import parse_duration
from ..utils.quantity import Quantity
from .ir import (MAX_ELEMS, MAX_GATHER, STR_LEN, TAG_ARRAY, TAG_BOOL,
                 TAG_FLOAT, TAG_INT, TAG_MAP, TAG_MISSING, TAG_NULL,
                 TAG_STRING, TAIL_LEN, CompiledPolicySet, GatherSlot, Slot,
                 StatusExpr)

_INT64_MAX = (1 << 63) - 1

_MISSING = object()

# lane bundles a slot/gather may need (computed from the ops that read it)
NEED_STR, NEED_MILLI, NEED_NANOS, NEED_WILD = 'str', 'milli', 'nanos', 'wild'


def _go_float_str(v: float) -> str:
    from ..engine.pattern import _go_format_float_e
    return _go_format_float_e(v)


def _sprint(v: Any) -> str:
    """Go fmt.Sprint for scalars (operators.py:_sprint)."""
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e21:
            return str(int(v))
        return repr(v)
    return str(v)


class Lanes:
    """numpy lane arrays for one slot or gather at a given shape."""

    def __init__(self, shape: Tuple[int, ...], needs: frozenset):
        self.needs = needs
        self.tag = np.zeros(shape, np.int8)
        z64 = lambda: np.zeros(shape, np.int64)  # noqa: E731
        zb = lambda: np.zeros(shape, bool)       # noqa: E731
        self.milli = z64() if NEED_MILLI in needs else None
        self.milli_ok = zb() if NEED_MILLI in needs else None
        self.nanos = z64() if NEED_NANOS in needs else None
        self.nanos_ok = zb() if NEED_NANOS in needs else None
        # the string-parse flags ride with whichever numeric/string bundle
        # reads them (cmp_qty gates on str_is_qty without string lanes)
        self.str_is_int = zb() if needs & {NEED_STR, NEED_MILLI} else None
        self.str_is_float = zb() if needs & {NEED_STR, NEED_MILLI} else None
        self.str_is_qty = zb() if NEED_MILLI in needs else None
        self.str_is_dur = zb() if NEED_NANOS in needs else None
        if NEED_STR in needs:
            self.str_len = np.zeros(shape, np.int32)
            self.str_head = np.zeros(shape + (STR_LEN,), np.uint8)
            self.str_tail = np.zeros(shape + (TAIL_LEN,), np.uint8)
        else:
            self.str_len = self.str_head = self.str_tail = None
        self.has_wild = zb() if NEED_WILD in needs else None

    _LANE_NAMES = ('tag', 'milli', 'milli_ok', 'nanos', 'nanos_ok',
                   'str_is_int', 'str_is_float', 'str_is_qty', 'str_is_dur',
                   'str_len', 'str_head', 'str_tail', 'has_wild')

    def tensors(self, prefix: str) -> Dict[str, np.ndarray]:
        out = {}
        for name in self._LANE_NAMES:
            v = getattr(self, name)
            if v is not None:
                out[f'{prefix}_{name}'] = v
        return out

    # -- value encoding ------------------------------------------------------

    def encode(self, idx, value: Any, string_form: Optional[str] = None,
               sprint_form: bool = False) -> None:
        """Encode one scalar value at ``idx``.

        ``sprint_form`` selects the operators' Go string form (gathers)
        over the pattern walk's float formatting (slots).
        """
        if value is _MISSING:
            self.tag[idx] = TAG_MISSING
            return
        if value is None:
            self.tag[idx] = TAG_NULL
            if self.milli is not None:
                self.milli_ok[idx] = True
            if self.nanos is not None:
                self.nanos_ok[idx] = True
            return
        if isinstance(value, bool):
            self.tag[idx] = TAG_BOOL
            if self.milli is not None:
                self.milli[idx] = 1000 if value else 0
                self.milli_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(idx, 'true' if value else 'false')
            return
        if isinstance(value, int):
            self.tag[idx] = TAG_INT
            if self.milli is not None and abs(value) <= _INT64_MAX // 1000:
                self.milli[idx] = value * 1000
                self.milli_ok[idx] = True
            if self.nanos is not None and value == 0:
                # _number_to_string(0) == '0' parses as Go duration 0
                self.nanos_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(idx, str(value))
            if self.str_is_int is not None:
                self.str_is_int[idx] = True
                self.str_is_float[idx] = True
            return
        if isinstance(value, float):
            self.tag[idx] = TAG_FLOAT
            if self.milli is not None and math.isfinite(value):
                frac = Fraction(str(value)) * 1000
                if frac.denominator == 1 and abs(frac.numerator) <= _INT64_MAX:
                    self.milli[idx] = int(frac)
                    self.milli_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(
                    idx, _sprint(value) if sprint_form
                    else _go_float_str(value))
            if self.str_is_float is not None:
                self.str_is_float[idx] = True
            return
        if isinstance(value, str):
            self.tag[idx] = TAG_STRING
            if self.str_len is not None:
                self._encode_str(idx, value)
            if self.str_is_int is not None:
                try:
                    int(value, 10)
                    self.str_is_int[idx] = True
                    self.str_is_float[idx] = True
                except ValueError:
                    try:
                        float(value)
                        self.str_is_float[idx] = True
                    except ValueError:
                        pass
            if self.has_wild is not None:
                self.has_wild[idx] = ('*' in value) or ('?' in value)
            if self.milli is not None:
                try:
                    q = Quantity.parse(value)
                except ValueError:
                    # int()-parseable strings the quantity grammar rejects
                    # (' 5', '5_0') still feed eq_int via the milli lane
                    try:
                        iv = int(value, 10)
                    except ValueError:
                        pass
                    else:
                        if abs(iv) <= _INT64_MAX // 1000:
                            self.milli[idx] = iv * 1000
                            self.milli_ok[idx] = True
                else:
                    if self.str_is_qty is not None:
                        self.str_is_qty[idx] = True
                    m = q.value * 1000
                    if m.denominator == 1 and abs(m.numerator) <= _INT64_MAX:
                        self.milli[idx] = int(m)
                        self.milli_ok[idx] = True
            if self.nanos is not None:
                try:
                    ns = parse_duration(value)
                except ValueError:
                    pass
                else:
                    if self.str_is_dur is not None:
                        self.str_is_dur[idx] = True
                    # str_is_dur without nanos_ok = parsed but out of the
                    # int64 lane → undecidable on device
                    if abs(ns) <= _INT64_MAX:
                        self.nanos[idx] = ns
                        self.nanos_ok[idx] = True
            return
        if isinstance(value, dict):
            self.tag[idx] = TAG_MAP
            return
        if isinstance(value, list):
            self.tag[idx] = TAG_ARRAY
            return
        self.tag[idx] = TAG_MISSING

    def _encode_str(self, idx, s: str) -> None:
        b = s.encode('utf-8')
        self.str_len[idx] = len(b)
        head = b[:STR_LEN]
        self.str_head[idx][:len(head)] = np.frombuffer(head, np.uint8)
        tail = b[-TAIL_LEN:]
        self.str_tail[idx][TAIL_LEN - len(tail):] = \
            np.frombuffer(tail, np.uint8)


# ---------------------------------------------------------------------------
# need analysis: which lanes each slot/gather requires

_STR_OPS = {'eq_str', 'prefix', 'suffix', 'min_len', 'nonempty', 'any_str',
            'convertible', 'eq_int', 'eq_float', 'eq_null', 'wildcard'}
_MILLI_OPS = {'eq_bool', 'eq_null', 'eq_int', 'eq_float', 'cmp_qty'}
_NANOS_OPS = {'cmp_dur'}

_ALL_NEEDS = frozenset({NEED_STR, NEED_MILLI, NEED_NANOS})


def _analyze_needs(cps: CompiledPolicySet):
    slot_needs: Dict[Slot, set] = {s: set() for s in cps.slots}
    gather_needs: Dict[GatherSlot, set] = {g: set() for g in cps.gathers}
    array_paths: set = set()

    def visit_bool(expr):
        if expr is None:
            return
        if expr.kind == 'leaf':
            leaf = expr.leaf
            n = slot_needs.setdefault(leaf.slot, set())
            if leaf.op in _STR_OPS:
                n.add(NEED_STR)
            if leaf.op in _MILLI_OPS:
                n.add(NEED_MILLI)
            if leaf.op in _NANOS_OPS:
                n.add(NEED_NANOS)
            return
        if expr.kind == 'cond':
            g = expr.cond.gather
            n = gather_needs.setdefault(g, set())
            # conditions may compare strings (with wildcards both ways),
            # quantities, and durations; encode everything they can read
            n.update((NEED_STR, NEED_MILLI, NEED_NANOS, NEED_WILD))
            return
        for c in expr.children:
            visit_bool(c)

    def visit_status(node: StatusExpr):
        if node is None:
            return
        visit_bool(node.expr)
        if node.kind in ('forall', 'exists', 'scalars') and \
                node.slot is not None:
            array_paths.add(node.slot.path)
        if node.sub is not None:
            visit_status(node.sub)
        for c in node.children:
            visit_status(c)

    for prog in cps.programs:
        visit_status(prog.status)
        # trackfail guards reduce element-scoped presence tests over the
        # containers along the slot path — those need count/overflow too
        def visit_guards(node: StatusExpr):
            if node is None:
                return
            if node.kind == 'trackfail' and node.expr is not None:
                def leaf_paths(e):
                    if e.kind == 'leaf' and e.leaf.slot.elem:
                        path = e.leaf.slot.path
                        for i, p in enumerate(path):
                            if p == '*':
                                array_paths.add(path[:i])
                    for c in e.children:
                        leaf_paths(c)
                leaf_paths(node.expr)
            if node.sub is not None:
                visit_guards(node.sub)
            for c in node.children:
                visit_guards(c)
        visit_guards(prog.status)
    # deterministic order shared by the encoder and the evaluator
    return slot_needs, gather_needs, sorted(array_paths)


# ---------------------------------------------------------------------------

def _walk(doc: Any, path: Tuple[str, ...]):
    cur = doc
    for key in path:
        if isinstance(cur, dict):
            if key not in cur:
                return _MISSING
            cur = cur[key]
        else:
            return _MISSING
    return cur


class Batch:
    def __init__(self, n: int):
        self.n = n
        self.slot_lanes: Dict[Slot, Lanes] = {}
        self.array_meta: Dict[Tuple[str, ...], Dict[str, np.ndarray]] = {}
        self.gather_lanes: Dict[GatherSlot, Lanes] = {}
        self.gather_meta: Dict[GatherSlot, Dict[str, np.ndarray]] = {}

    def tensors(self) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for i, (slot, lanes) in enumerate(self.slot_lanes.items()):
            out.update(lanes.tensors(f's{i}'))
        for j, (path, meta) in enumerate(self.array_meta.items()):
            out[f'a{j}_count'] = meta['count']
            out[f'a{j}_overflow'] = meta['overflow']
            out[f'a{j}_tag'] = meta['tag']
        for k, (g, lanes) in enumerate(self.gather_lanes.items()):
            out.update(lanes.tensors(f'g{k}'))
            meta = self.gather_meta[g]
            out[f'g{k}_kind'] = meta['kind']
            out[f'g{k}_count'] = meta['count']
            out[f'g{k}_overflow'] = meta['overflow']
            out[f'g{k}_notfound'] = meta['notfound']
        return out


def encode_batch(resources: List[dict], cps: CompiledPolicySet,
                 padded_n: int = 0) -> Batch:
    n = max(len(resources), padded_n)
    batch = Batch(n)
    slot_needs, gather_needs, array_paths = _needs_cached(cps)

    # array metadata channels (count/overflow/tag) for forall/exists nodes
    for path in array_paths:
        depth = sum(1 for p in path if p == '*')
        shape = (n,) + (MAX_ELEMS,) * depth
        batch.array_meta[path] = {
            'count': np.zeros(shape, np.int32),
            'overflow': np.zeros(shape, bool),
            'tag': np.zeros(shape, np.int8),
        }

    for slot in cps.slots:
        shape = (n,) + (MAX_ELEMS,) * slot.depth
        batch.slot_lanes[slot] = Lanes(shape, frozenset(slot_needs[slot]))

    for g in cps.gathers:
        batch.gather_lanes[g] = Lanes((n, MAX_GATHER),
                                      frozenset(gather_needs[g]))
        batch.gather_meta[g] = {
            'kind': np.zeros(n, np.int8),
            'count': np.zeros(n, np.int32),
            'overflow': np.zeros(n, bool),
            'notfound': np.zeros(n, bool),
        }

    gather_progs = [(g, batch.gather_lanes[g], batch.gather_meta[g],
                     _gather_searcher(g)) for g in cps.gathers]

    slot_plan = _slot_plan(cps, batch)
    for r, doc in enumerate(resources):
        _encode_doc(r, doc, slot_plan, batch)
        for g, lanes, meta, searcher in gather_progs:
            _encode_gather(r, doc, lanes, meta, searcher)
    return batch


def _needs_cached(cps: CompiledPolicySet):
    cached = getattr(cps, '_needs_cache', None)
    if cached is None:
        cached = _analyze_needs(cps)
        cps._needs_cache = cached
    return cached


def _slot_plan(cps: CompiledPolicySet, batch: Batch):
    """Group slots by their first array prefix so arrays are walked once."""
    plan = []
    for slot in cps.slots:
        lanes = batch.slot_lanes[slot]
        plan.append((slot, lanes))
    return plan


def _encode_doc(r: int, doc: dict, slot_plan, batch: Batch) -> None:
    for path, meta in batch.array_meta.items():
        _encode_array_meta(r, doc, path, meta)
    for slot, lanes in slot_plan:
        if slot.depth == 0:
            lanes.encode(r, _walk(doc, slot.path))
            continue
        star1 = slot.path.index('*')
        container = _walk(doc, slot.path[:star1])
        rest1 = slot.path[star1 + 1:]
        if not isinstance(container, list):
            continue  # lanes stay TAG_MISSING; array guards handle it
        if slot.depth == 1:
            for e, elem in enumerate(container[:MAX_ELEMS]):
                value = _walk(elem, rest1) if rest1 else elem
                if rest1 and not isinstance(elem, dict):
                    value = _MISSING
                lanes.encode((r, e), value)
        else:
            star2 = rest1.index('*')
            mid, rest2 = rest1[:star2], rest1[star2 + 1:]
            for e, elem in enumerate(container[:MAX_ELEMS]):
                inner = _walk(elem, mid) if isinstance(elem, dict) else _MISSING
                if not isinstance(inner, list):
                    continue
                for e2, elem2 in enumerate(inner[:MAX_ELEMS]):
                    value = elem2
                    if rest2:
                        value = _walk(elem2, rest2) \
                            if isinstance(elem2, dict) else _MISSING
                    lanes.encode((r, e, e2), value)


def _encode_array_meta(r: int, doc: dict, path: Tuple[str, ...],
                       meta: Dict[str, np.ndarray]) -> None:
    depth = sum(1 for p in path if p == '*')
    if depth == 0:
        value = _walk(doc, path)
        _set_array_meta(meta, r, value)
        return
    star1 = path.index('*')
    container = _walk(doc, path[:star1])
    rest = path[star1 + 1:]
    if not isinstance(container, list):
        return
    for e, elem in enumerate(container[:MAX_ELEMS]):
        value = _walk(elem, rest) if isinstance(elem, dict) else _MISSING
        _set_array_meta(meta, (r, e), value)


def _set_array_meta(meta, idx, value) -> None:
    if value is _MISSING:
        meta['tag'][idx] = TAG_MISSING
    elif isinstance(value, list):
        meta['tag'][idx] = TAG_ARRAY
        meta['count'][idx] = min(len(value), MAX_ELEMS)
        meta['overflow'][idx] = len(value) > MAX_ELEMS
    elif value is None:
        meta['tag'][idx] = TAG_NULL
    elif isinstance(value, dict):
        meta['tag'][idx] = TAG_MAP
    else:
        meta['tag'][idx] = TAG_STRING  # non-array scalar: guards only


def _gather_searcher(g: GatherSlot):
    from ..engine.jmespath import compile as jp_compile
    compiled = jp_compile(g.expr)
    return compiled


def _encode_gather(r: int, doc: dict, lanes: Lanes, meta, searcher) -> None:
    from ..engine.jmespath import NotFoundError
    try:
        result = searcher.search({'request': {'object': doc}})
    except NotFoundError:
        # missing path → the host's deterministic substitution-error ERROR
        # (engine.py:388; synthesized on device via STATUS_VAR_ERR)
        meta['kind'][r] = 0
        meta['notfound'][r] = True
        return
    except Exception:  # noqa: BLE001 - interpreter error → host decides
        meta['kind'][r] = 0
        meta['overflow'][r] = True
        return
    if result is None:
        meta['kind'][r] = 0
        return
    if isinstance(result, list):
        meta['kind'][r] = 2
        meta['count'][r] = min(len(result), MAX_GATHER)
        if len(result) > MAX_GATHER:
            meta['overflow'][r] = True
        for e, value in enumerate(result[:MAX_GATHER]):
            lanes.encode((r, e), value, sprint_form=True)
        return
    meta['kind'][r] = 1
    meta['count'][r] = 1
    lanes.encode((r, 0), result, sprint_form=True)
