"""Batch encoder: resources → fixed-shape slot tensors.

Projects each resource onto the compiled slot table (the document never
reaches the device). Encoding is conservative toward FAIL: any value the
encoder cannot represent exactly gets invalid flags, which can only turn a
device PASS into a device non-pass — and all non-pass verdicts are
re-materialized by the host engine, so correctness is preserved.

Channels per slot (scalar slots shape [R], element slots [R, E]):
  tag        i8   type tag (ir.TAG_*)
  milli      i64  numeric value ×1000 (ints exact; quantities; null → 0)
  milli_ok   bool
  nanos      i64  Go duration in ns (strings with units; null → 0)
  nanos_ok   bool
  str_is_int / str_is_float  bool  (string parse classes)
  str_len    i32  byte length of the value's Go string form
  str_head   u8[STR_LEN]  first bytes
  str_tail   u8[TAIL_LEN] last bytes, right-aligned
Arrays referenced by element blocks additionally get:
  arr_tag    i8   tag of the array node itself
  elem_count i32
"""

from __future__ import annotations

import math
from fractions import Fraction
from typing import Any, Dict, List, Tuple

import numpy as np

from ..utils.duration import parse_duration
from ..utils.quantity import Quantity
from .ir import (MAX_ELEMS, STR_LEN, TAG_ARRAY, TAG_BOOL, TAG_FLOAT, TAG_INT,
                 TAG_MAP, TAG_MISSING, TAG_NULL, TAG_STRING,
                 CompiledPolicySet, Slot)

TAIL_LEN = 16

_INT64_MAX = (1 << 63) - 1


def _go_float_str(v: float) -> str:
    from ..engine.pattern import _go_format_float_e
    return _go_format_float_e(v)


class SlotArrays:
    """numpy arrays for one slot."""

    def __init__(self, n: int, elem: bool):
        shape = (n, MAX_ELEMS) if elem else (n,)
        self.tag = np.zeros(shape, np.int8)
        self.milli = np.zeros(shape, np.int64)
        self.milli_ok = np.zeros(shape, bool)
        self.nanos = np.zeros(shape, np.int64)
        self.nanos_ok = np.zeros(shape, bool)
        self.str_is_int = np.zeros(shape, bool)
        self.str_is_float = np.zeros(shape, bool)
        self.str_len = np.zeros(shape, np.int32)
        self.str_head = np.zeros(shape + (STR_LEN,), np.uint8)
        self.str_tail = np.zeros(shape + (TAIL_LEN,), np.uint8)

    def tensors(self) -> Dict[str, np.ndarray]:
        return {k: getattr(self, k) for k in (
            'tag', 'milli', 'milli_ok', 'nanos', 'nanos_ok', 'str_is_int',
            'str_is_float', 'str_len', 'str_head', 'str_tail')}


class Batch:
    def __init__(self, n: int):
        self.n = n
        self.slots: Dict[Slot, SlotArrays] = {}
        self.arrays: Dict[Tuple[str, ...], Dict[str, np.ndarray]] = {}

    def tensors(self) -> Dict[str, np.ndarray]:
        out = {}
        for i, (slot, arrs) in enumerate(self.slots.items()):
            for k, v in arrs.tensors().items():
                out[f's{i}_{k}'] = v
        for j, (path, d) in enumerate(self.arrays.items()):
            out[f'a{j}_tag'] = d['arr_tag']
            out[f'a{j}_count'] = d['elem_count']
        return out


def _walk(doc: Any, path: Tuple[str, ...]):
    """Resolve a structural path; yields the value or a marker."""
    cur = doc
    for key in path:
        if key == '*':
            return cur  # caller handles element expansion
        if isinstance(cur, dict):
            if key not in cur:
                return _MISSING
            cur = cur[key]
        else:
            return _MISSING
    return cur


_MISSING = object()


_ALL_NEEDS = (True, True, True)


def _encode_value(arrs: SlotArrays, idx, value: Any,
                  need=_ALL_NEEDS) -> None:
    t = arrs
    need_str, need_milli, need_nanos = need
    if value is _MISSING:
        t.tag[idx] = TAG_MISSING
        return
    if value is None:
        t.tag[idx] = TAG_NULL
        t.milli_ok[idx] = True
        t.nanos_ok[idx] = True
        return
    if isinstance(value, bool):
        t.tag[idx] = TAG_BOOL
        t.milli[idx] = 1000 if value else 0
        t.milli_ok[idx] = True
        if need_str:
            _encode_str(t, idx, 'true' if value else 'false')
        return
    if isinstance(value, int):
        t.tag[idx] = TAG_INT
        if abs(value) <= _INT64_MAX // 1000:
            t.milli[idx] = value * 1000
            t.milli_ok[idx] = True
        if need_str:
            _encode_str(t, idx, str(value))
        return
    if isinstance(value, float):
        t.tag[idx] = TAG_FLOAT
        if need_milli and math.isfinite(value):
            frac = Fraction(str(value)) * 1000
            if frac.denominator == 1 and abs(frac.numerator) <= _INT64_MAX:
                t.milli[idx] = int(frac)
                t.milli_ok[idx] = True
        if need_str:
            _encode_str(t, idx, _go_float_str(value))
        return
    if isinstance(value, str):
        t.tag[idx] = TAG_STRING
        if need_str:
            _encode_str(t, idx, value)
            s = value
            try:
                int(s, 10)
                t.str_is_int[idx] = True
                t.str_is_float[idx] = True
            except ValueError:
                try:
                    float(s)
                    t.str_is_float[idx] = True
                except ValueError:
                    pass
        if need_milli:
            try:
                q = Quantity.parse(value)
                m = q.value * 1000
                if m.denominator == 1 and abs(m.numerator) <= _INT64_MAX:
                    t.milli[idx] = int(m)
                    t.milli_ok[idx] = True
            except ValueError:
                pass
        if need_nanos:
            try:
                t.nanos[idx] = parse_duration(value)
                t.nanos_ok[idx] = True
            except ValueError:
                pass
        return
    if isinstance(value, dict):
        t.tag[idx] = TAG_MAP
        return
    if isinstance(value, list):
        t.tag[idx] = TAG_ARRAY
        return
    t.tag[idx] = TAG_MISSING


def _encode_str(t: SlotArrays, idx, s: str) -> None:
    b = s.encode('utf-8')
    t.str_len[idx] = len(b)
    head = b[:STR_LEN]
    t.str_head[idx][:len(head)] = np.frombuffer(head, np.uint8)
    tail = b[-TAIL_LEN:]
    # right-aligned tail
    t.str_tail[idx][TAIL_LEN - len(tail):] = np.frombuffer(tail, np.uint8)


_STR_OPS = {'eq_str', 'prefix', 'suffix', 'min_len', 'nonempty', 'any_str',
            'convertible', 'eq_int', 'eq_float'}
_MILLI_OPS = {'eq_bool', 'eq_null', 'eq_int', 'eq_float', 'cmp_qty'}
_NANOS_OPS = {'cmp_dur'}


def _slot_needs(cps: CompiledPolicySet) -> Dict[Slot, Tuple[bool, bool, bool]]:
    """Which channels each slot actually requires (str, milli, nanos)."""
    cached = getattr(cps, '_slot_needs_cache', None)
    if cached is not None:
        return cached
    needs: Dict[Slot, List[bool]] = {s: [False, False, False]
                                     for s in cps.slots}

    def visit(expr):
        if expr is None:
            return
        if expr.kind == 'leaf':
            leaf = expr.leaf
            n = needs.setdefault(leaf.slot, [False, False, False])
            if leaf.op in _STR_OPS:
                n[0] = True
            if leaf.op in _MILLI_OPS:
                n[1] = True
            if leaf.op in _NANOS_OPS:
                n[2] = True
        for c in expr.children:
            visit(c)

    for prog in cps.programs:
        visit(prog.scalar)
        visit(prog.scalar_condition)
        for block in prog.elements:
            visit(block.condition)
            visit(block.constraint)
    out = {s: tuple(v) for s, v in needs.items()}
    cps._slot_needs_cache = out
    return out


def encode_batch(resources: List[dict], cps: CompiledPolicySet,
                 padded_n: int = 0) -> Batch:
    n = max(len(resources), padded_n)
    batch = Batch(n)
    needs = _slot_needs(cps)
    # collect array paths used by element blocks
    array_paths = set()
    for prog in cps.programs:
        for block in prog.elements:
            array_paths.add(block.array_path)
    for path in array_paths:
        batch.arrays[path] = {
            'arr_tag': np.zeros(n, np.int8),
            'elem_count': np.zeros(n, np.int32),
        }
    for slot in cps.slots:
        batch.slots[slot] = SlotArrays(n, slot.elem)

    slot_plan = [(slot, arrs, needs.get(slot, (True, True, True)))
                 for slot, arrs in batch.slots.items()]
    for r, doc in enumerate(resources):
        for path, arrs in batch.arrays.items():
            value = _walk(doc, path)
            if value is _MISSING:
                arrs['arr_tag'][r] = TAG_MISSING
            elif isinstance(value, list):
                arrs['arr_tag'][r] = TAG_ARRAY
                arrs['elem_count'][r] = min(len(value), MAX_ELEMS)
                if len(value) > MAX_ELEMS:
                    # overflow: force host fallback by marking invalid
                    arrs['arr_tag'][r] = TAG_MAP
            else:
                arrs['arr_tag'][r] = TAG_MAP  # wrong type → device FAIL
        for slot, arrs, need in slot_plan:
            if not slot.elem:
                _encode_value(arrs, r, _walk(doc, slot.path), need)
                continue
            star = slot.path.index('*')
            container = _walk(doc, slot.path[:star])
            rest = slot.path[star + 1:]
            if not isinstance(container, list):
                continue  # stays MISSING; block-level arr_tag handles it
            for e, elem in enumerate(container[:MAX_ELEMS]):
                if rest:
                    value = _walk(elem, rest) if isinstance(elem, dict) \
                        else _MISSING
                else:
                    value = elem
                _encode_value(arrs, (r, e), value, need)
    return batch
