"""Batch encoder v2: resources → fixed-shape slot + gather tensors.

Projects each resource onto the compiled slot table and evaluates gather
expressions with the in-repo JMESPath interpreter (the document itself
never reaches the device).  Encoding is conservative toward UNKNOWN: any
value the encoder cannot represent exactly sets flags that make the
device evaluator emit STATUS_HOST, after which the host engine re-runs
that (resource, rule) pair — correctness is never lost.

Lane schema (shared by slots and gather elements; shapes are [R],
[R, E], [R, E, E2] for slots by star-depth, [R, G] for gathers):
  tag        i8   type tag (ir.TAG_*)
  milli      i64  numeric value ×1000 (ints exact; quantity strings)
  milli_ok   bool milli lane is exact
  nanos      i64  Go duration in ns (strings with units)
  nanos_ok   bool
  str_is_int / str_is_float / str_is_qty / str_is_dur   bool
  has_wild   bool value's string form contains * or ? (gathers only)
  str_len    i32  byte length of the value's string form
  str_head   u8[STR_LEN]  first bytes
  str_tail   u8[TAIL_LEN] last bytes, right-aligned
Array nodes referenced by forall/exists additionally get, keyed by path:
  count      i32  number of elements (clamped to MAX_ELEMS)
  overflow   bool more than MAX_ELEMS elements → device UNKNOWN
Gathers additionally get:
  kind       i8   0 = null/absent, 1 = scalar, 2 = list
  count      i32
  overflow   bool
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from fractions import Fraction
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.duration import parse_duration
from ..utils.quantity import Quantity
from ..utils.wildcard import match as _wild_match
from .ir import (MAX_ELEMS, MAX_GATHER, STR_LEN, TAG_ARRAY, TAG_BOOL,
                 TAG_FLOAT, TAG_INT, TAG_MAP, TAG_MISSING, TAG_NULL,
                 TAG_STRING, TAIL_LEN, CompiledPolicySet, GatherSlot, Slot,
                 StatusExpr)

_INT64_MAX = (1 << 63) - 1

_MISSING = object()

# Per-slot/gather lane requirements, computed from exactly the ops the
# evaluator performs against it (ops/eval.py read-set).  ``head`` is the
# byte width of the string-head window — sized to the longest constant a
# comparison needs, not a fixed 64 — which is the dominant memory/transfer
# term of the encoded batch.
@dataclass
class LaneNeeds:
    head: int = 0
    tail: bool = False
    length: bool = False
    milli: bool = False
    nanos: bool = False
    wild: bool = False
    lit_zero: bool = False

    def merge(self, other: 'LaneNeeds') -> None:
        self.head = max(self.head, other.head)
        self.tail = self.tail or other.tail
        self.length = self.length or other.length
        self.milli = self.milli or other.milli
        self.nanos = self.nanos or other.nanos
        self.wild = self.wild or other.wild
        self.lit_zero = self.lit_zero or other.lit_zero

    def add_pattern(self, pattern: str) -> None:
        """Lanes read by a constant glob comparison (ir.classify_wildcard
        keeps this in sync with eval._View.match_const_pattern)."""
        from .ir import classify_wildcard
        kind, parts = classify_wildcard(pattern)
        if kind == 'eq':
            self.head = max(self.head, len(parts[0].encode('utf-8')))
            self.length = True
        elif kind == 'nonempty':
            self.length = True
        elif kind == 'prefix':
            self.head = max(self.head, len(parts[0].encode('utf-8')))
            self.length = True
        elif kind == 'suffix':
            self.tail = True
            self.length = True
        elif kind == 'prefix_suffix':
            self.head = max(self.head, len(parts[0].encode('utf-8')))
            self.tail = True
            self.length = True
        elif kind == 'dp':
            self.head = STR_LEN
            self.length = True
        # 'any' reads only the tag


def _go_float_str(v: float) -> str:
    from ..engine.pattern import _go_format_float_e
    return _go_format_float_e(v)


def _sprint(v: Any) -> str:
    """Go fmt.Sprint for scalars (operators.py:_sprint)."""
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e21:
            return str(int(v))
        return repr(v)
    return str(v)


class Lanes:
    """numpy lane arrays for one slot or gather at a given shape, sized to
    exactly the lanes (and head byte width) its comparisons read."""

    def __init__(self, shape: Tuple[int, ...], needs: LaneNeeds):
        self.needs = needs
        self.tag = np.zeros(shape, np.int8)
        z64 = lambda: np.zeros(shape, np.int64)  # noqa: E731
        zb = lambda: np.zeros(shape, bool)       # noqa: E731
        self.milli = z64() if needs.milli else None
        self.milli_ok = zb() if needs.milli else None
        self.nanos = z64() if needs.nanos else None
        self.nanos_ok = zb() if needs.nanos else None
        # the string-parse flags ride with the numeric bundle that gates
        # on them (eq_int/str_is_qty read milli; str_is_dur reads nanos)
        self.str_is_int = zb() if needs.milli else None
        self.str_is_float = zb() if needs.milli else None
        self.str_is_qty = zb() if needs.milli else None
        self.str_is_dur = zb() if needs.nanos else None
        self.lit_zero = zb() if needs.lit_zero else None
        if needs.length or needs.head or needs.tail:
            self.str_len = np.zeros(shape, np.int32)
        else:
            self.str_len = None
        if needs.head:
            # round the head window up for alignment / fewer pack groups
            w = min(STR_LEN, (needs.head + 7) & ~7)
            self.str_head = np.zeros(shape + (w,), np.uint8)
        else:
            self.str_head = None
        self.str_tail = np.zeros(shape + (TAIL_LEN,), np.uint8) \
            if needs.tail else None
        self.has_wild = zb() if needs.wild else None

    _LANE_NAMES = ('tag', 'milli', 'milli_ok', 'nanos', 'nanos_ok',
                   'str_is_int', 'str_is_float', 'str_is_qty', 'str_is_dur',
                   'lit_zero', 'str_len', 'str_head', 'str_tail', 'has_wild')

    def tensors(self, prefix: str) -> Dict[str, np.ndarray]:
        out = {}
        for name in self._LANE_NAMES:
            v = getattr(self, name)
            if v is not None:
                out[f'{prefix}_{name}'] = v
        return out

    def clear(self) -> None:
        """Zero every lane in place (arena reuse between chunks)."""
        for name in self._LANE_NAMES:
            v = getattr(self, name)
            if v is not None:
                v.fill(0)

    def encode_column(self, idx, values: list, palette: '_Palette') -> None:
        """Columnar encode: dictionary-encode ``values`` through
        ``palette`` (one scalar :meth:`encode` per DISTINCT value, ever)
        and scatter the palette rows into the lanes with one vectorized
        assignment per lane.  ``idx`` is ``None`` for a full leading-
        rows column (rows ``0..len(values)``) or a tuple of equal-length
        index arrays for element-scoped columns."""
        if not values:
            return
        with palette.lock:
            codes = palette.codes_for(values)
            src = palette.lanes
            m = len(values)
            for name in self._LANE_NAMES:
                dst = getattr(self, name)
                if dst is None:
                    continue
                s = getattr(src, name)
                if idx is None:
                    dst[:m] = s[codes]
                else:
                    dst[idx] = s[codes]

    # -- value encoding ------------------------------------------------------

    def encode(self, idx, value: Any, string_form: Optional[str] = None,
               sprint_form: bool = False) -> None:
        """Encode one scalar value at ``idx``.

        ``sprint_form`` selects the operators' Go string form (gathers)
        over the pattern walk's float formatting (slots).
        """
        if value is _MISSING:
            self.tag[idx] = TAG_MISSING
            return
        if value is None:
            self.tag[idx] = TAG_NULL
            if self.milli is not None:
                self.milli_ok[idx] = True
            if self.nanos is not None:
                self.nanos_ok[idx] = True
            return
        if isinstance(value, bool):
            self.tag[idx] = TAG_BOOL
            if self.milli is not None:
                self.milli[idx] = 1000 if value else 0
                self.milli_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(idx, 'true' if value else 'false')
            return
        if isinstance(value, int):
            self.tag[idx] = TAG_INT
            if self.milli is not None and abs(value) <= _INT64_MAX // 1000:
                self.milli[idx] = value * 1000
                self.milli_ok[idx] = True
            if self.nanos is not None and value == 0:
                # _number_to_string(0) == '0' parses as Go duration 0
                self.nanos_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(idx, str(value))
            if self.str_is_int is not None:
                self.str_is_int[idx] = True
                self.str_is_float[idx] = True
            return
        if isinstance(value, float):
            self.tag[idx] = TAG_FLOAT
            if self.milli is not None and math.isfinite(value):
                frac = Fraction(str(value)) * 1000
                if frac.denominator == 1 and abs(frac.numerator) <= _INT64_MAX:
                    self.milli[idx] = int(frac)
                    self.milli_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(
                    idx, _sprint(value) if sprint_form
                    else _go_float_str(value))
            if self.str_is_float is not None:
                self.str_is_float[idx] = True
            return
        if isinstance(value, str):
            self.tag[idx] = TAG_STRING
            if self.str_len is not None:
                self._encode_str(idx, value)
            if self.lit_zero is not None and value == '0':
                self.lit_zero[idx] = True
            if self.str_is_int is not None:
                try:
                    int(value, 10)
                    self.str_is_int[idx] = True
                    self.str_is_float[idx] = True
                except ValueError:
                    try:
                        float(value)
                        self.str_is_float[idx] = True
                    except ValueError:
                        pass
            if self.has_wild is not None:
                self.has_wild[idx] = ('*' in value) or ('?' in value)
            if self.milli is not None:
                try:
                    q = Quantity.parse(value)
                except ValueError:
                    # int()-parseable strings the quantity grammar rejects
                    # (' 5', '5_0') still feed eq_int via the milli lane
                    try:
                        iv = int(value, 10)
                    except ValueError:
                        pass
                    else:
                        if abs(iv) <= _INT64_MAX // 1000:
                            self.milli[idx] = iv * 1000
                            self.milli_ok[idx] = True
                else:
                    if self.str_is_qty is not None:
                        self.str_is_qty[idx] = True
                    m = q.value * 1000
                    if m.denominator == 1 and abs(m.numerator) <= _INT64_MAX:
                        self.milli[idx] = int(m)
                        self.milli_ok[idx] = True
            if self.nanos is not None:
                try:
                    ns = parse_duration(value)
                except ValueError:
                    pass
                else:
                    if self.str_is_dur is not None:
                        self.str_is_dur[idx] = True
                    # str_is_dur without nanos_ok = parsed but out of the
                    # int64 lane → undecidable on device
                    if abs(ns) <= _INT64_MAX:
                        self.nanos[idx] = ns
                        self.nanos_ok[idx] = True
            return
        if isinstance(value, dict):
            self.tag[idx] = TAG_MAP
            return
        if isinstance(value, list):
            self.tag[idx] = TAG_ARRAY
            return
        self.tag[idx] = TAG_MISSING

    def _encode_str(self, idx, s: str) -> None:
        b = s.encode('utf-8')
        self.str_len[idx] = len(b)
        if self.str_head is not None:
            w = self.str_head.shape[-1]
            head = b[:w]
            self.str_head[idx][:len(head)] = np.frombuffer(head, np.uint8)
        if self.str_tail is not None:
            tail = b[-TAIL_LEN:]
            self.str_tail[idx][TAIL_LEN - len(tail):] = \
                np.frombuffer(tail, np.uint8)


# ---------------------------------------------------------------------------
# columnar dictionary encoding: one scalar encode per DISTINCT value

#: singleton palette keys for the classes whose encoding ignores the
#: value (encode() writes only the type tag for these)
_KEY_MAP = ('__map__',)
_KEY_ARR = ('__array__',)
_KEY_OTHER = ('__other__',)
_KEY_NONE = ('__null__',)
_KEY_MISSING = ('__missing__',)


class _Palette:
    """Dictionary encoder for one lane column (slot or gather).

    Values in a policy-scan batch repeat massively — image names,
    booleans, quantity strings, label values — so the palette runs the
    scalar :meth:`Lanes.encode` once per distinct value and remembers
    the encoded lane row; subsequent chunks pay one dict lookup per
    value instead of a dozen numpy scalar writes.  Palettes persist
    across chunks on the :class:`LaneArena`, so a steady-state stream
    encodes almost entirely through vectorized gathers."""

    __slots__ = ('lanes', 'needs', 'sprint', 'codes', 'cap', 'lock')

    #: distinct-value bound: a column exceeding it (adversarial
    #: high-cardinality values) resets rather than growing unbounded
    MAX_ENTRIES = 65536

    def __init__(self, needs: LaneNeeds, sprint: bool):
        self.needs = needs
        self.sprint = sprint
        self.cap = 64
        self.lanes = Lanes((self.cap,), needs)
        self.codes: Dict[tuple, int] = {}
        self.lock = __import__('threading').Lock()

    def _grow(self) -> None:
        new_cap = self.cap * 2
        new = Lanes((new_cap,), self.needs)
        for name in Lanes._LANE_NAMES:
            src = getattr(self.lanes, name)
            if src is not None:
                getattr(new, name)[:self.cap] = src
        self.lanes = new
        self.cap = new_cap

    def _key(self, value: Any) -> tuple:
        # mirrors the isinstance ladder of Lanes.encode exactly: two
        # values share a palette row only when encode() cannot tell
        # them apart
        if value is _MISSING:
            return _KEY_MISSING
        if value is None:
            return _KEY_NONE
        if isinstance(value, bool):
            return (bool, value)
        if isinstance(value, int):
            return (int, value)
        if isinstance(value, float):
            # repr distinguishes -0.0 from 0.0 (their Go string forms
            # differ) and collapses every NaN onto one row
            return (float, repr(value))
        if isinstance(value, str):
            return (str, value)
        if isinstance(value, dict):
            return _KEY_MAP
        if isinstance(value, list):
            return _KEY_ARR
        return _KEY_OTHER

    def code(self, value: Any) -> int:
        key = self._key(value)
        c = self.codes.get(key)
        if c is None:
            if len(self.codes) >= self.MAX_ENTRIES:
                self.codes.clear()
                self.lanes.clear()
            c = len(self.codes)
            if c >= self.cap:
                self._grow()
            self.lanes.encode(c, value, sprint_form=self.sprint)
            self.codes[key] = c
        return c

    def codes_for(self, values: list) -> np.ndarray:
        return np.fromiter(map(self.code, values), np.intp,
                           count=len(values))


class LaneArena:
    """Bounded pool of reusable encode buffers plus the cross-chunk
    palettes for one compiled policy set.

    The streaming scan pipeline holds a small fixed number of chunks in
    flight; the arena recycles their lane tensors (zeroed in place)
    instead of allocating ~100MB of numpy arrays per chunk, which is
    what kept the 1M-resource path allocating monotonically.  A batch
    is released back only after its device inputs are freed (d2h
    complete), so a zero-copy host-to-device path can never observe a
    recycled buffer."""

    def __init__(self, max_pool: int = 4):
        #: buffers kept per shape key; 0 = palettes only (forked encode
        #: workers pickle tensors after return, so recycling there could
        #: zero a buffer mid-serialization)
        self.max_pool = max_pool
        self._lock = __import__('threading').Lock()
        self._free: Dict[tuple, List['Batch']] = {}
        self._palettes: Dict[tuple, _Palette] = {}

    def palette(self, key: tuple, needs: LaneNeeds,
                sprint: bool) -> _Palette:
        with self._lock:
            pal = self._palettes.get(key)
            if pal is None:
                pal = self._palettes[key] = _Palette(needs, sprint)
            return pal

    def acquire(self, key: tuple) -> Optional['Batch']:
        with self._lock:
            pool = self._free.get(key)
            if pool:
                return pool.pop()
        return None

    def release(self, batch: 'Batch') -> None:
        key = getattr(batch, 'arena_key', None)
        if key is None:
            return
        with self._lock:
            pool = self._free.setdefault(key, [])
            if len(pool) < self.max_pool:
                pool.append(batch)


# ---------------------------------------------------------------------------
# need analysis: which lanes each slot/gather requires (mirrors the exact
# read-set of ops/eval.py for each leaf op / condition check)

def _blen(s: str) -> int:
    # floor 1: ops that compare against '' still read the str_head lane
    # (eval.py eq_const), so the window must exist even for empty
    # constants
    return min(max(len(s.encode('utf-8')), 1), STR_LEN)


def _leaf_needs(op: str, operand: Any) -> LaneNeeds:
    n = LaneNeeds()
    if op in ('eq_bool', 'eq_int', 'eq_float', 'cmp_qty',
              'is_true', 'is_false', 'is_zero_num'):
        n.milli = True
    if op == 'truthy':
        n.milli = True
        n.length = True
    if op == 'eq_null':
        n.milli = True
        n.length = True
    if op == 'cmp_dur':
        n.nanos = True
    if op in ('eq_str', 'prefix'):
        n.head = _blen(operand)
        n.length = True
    if op == 'suffix':
        n.tail = True
        n.length = True
    if op in ('min_len', 'nonempty'):
        n.length = True
    if op == 'wildcard':
        n.head = STR_LEN
        n.length = True
    return n


_IN_FAMILY = ('in', 'anyin', 'allin', 'notin', 'anynotin', 'allnotin')


def _cond_needs(check) -> LaneNeeds:
    """Gather lanes read by one condition check (ops/eval.py cond_tf)."""
    from ..engine import pattern as leaf_pattern
    n = LaneNeeds()
    op = check.op
    if op in ('equal', 'equals', 'notequal', 'notequals'):
        if check.list_value:
            for cv in check.values:
                if isinstance(cv, str):
                    n.head = max(n.head, _blen(cv))
                    n.length = True
                elif isinstance(cv, (bool, int, float)):
                    n.milli = True
        else:
            v = check.values[0]
            if isinstance(v, bool):
                n.milli = True
            elif isinstance(v, (int, float)):
                n.milli = True
                n.nanos = True
                n.lit_zero = True
            elif isinstance(v, str):
                n.milli = True
                n.nanos = True
                n.lit_zero = True
                n.length = True
                n.head = max(n.head, _blen(v))
                n.add_pattern(v)
    elif op in _IN_FAMILY:
        if check.list_value:
            n.wild = True
            n.length = True
            for cv in check.values:
                vs = cv if isinstance(cv, str) else _sprint(cv)
                n.add_pattern(vs)
                n.head = max(n.head, _blen(vs))
        else:
            v = check.values[0]
            if isinstance(v, str):
                n.length = True
                n.head = max(n.head, _blen(v))
                n.add_pattern(v)
                if leaf_pattern.get_operator_from_string_pattern(v) == \
                        leaf_pattern.OP_IN_RANGE:
                    n.milli = True
                    n.nanos = True
                else:
                    # list keys run _both_dir_member over the parsed
                    # JSON elements (or [v] itself): wildcard matching in
                    # both directions needs has_wild plus the per-element
                    # pattern windows (eval.py _in_family_tf)
                    n.wild = True
                    import json as _json
                    try:
                        arr = _json.loads(v)
                    except ValueError:
                        arr = None
                    elems = [x for x in arr if isinstance(x, str)] \
                        if isinstance(arr, list) else [v]
                    for x in elems:
                        n.head = max(n.head, _blen(x))
                        n.add_pattern(x)
    else:  # numeric comparisons
        n.milli = True
        n.nanos = True
        n.lit_zero = True
    return n


def _cond_b_needs(check) -> LaneNeeds:
    """Value-gather lanes read by a mode-B check (const key vs gather
    value; ops/eval.py _cond_b_tf)."""
    n = LaneNeeds()
    key = check.key_const
    op = check.op
    if op in ('equal', 'equals', 'notequal', 'notequals'):
        if isinstance(key, bool):
            n.milli = True
        elif isinstance(key, (int, float)):
            n.milli = True
        elif isinstance(key, str):
            n.milli = True
            n.nanos = True
            n.lit_zero = True
            n.length = True
            n.wild = True
            n.head = max(n.head, _blen(key))
    else:  # in-family with scalar const key
        ks = key if isinstance(key, str) else _sprint(key)
        n.length = True
        n.wild = True
        # the scalar-value suspicion scan marks values longer than the
        # window as undecidable (host re-run), so a narrow head suffices
        n.head = max(16, _blen(ks))
        n.add_pattern(ks)
    return n


def _analyze_needs(cps: CompiledPolicySet):
    slot_needs: Dict[Slot, LaneNeeds] = {s: LaneNeeds() for s in cps.slots}
    gather_needs: Dict[GatherSlot, LaneNeeds] = \
        {g: LaneNeeds() for g in cps.gathers}
    elem_needs: Dict = {g: LaneNeeds() for g in cps.elem_gathers}
    array_paths: set = set()

    def visit_bool(expr):
        if expr is None:
            return
        if expr.kind == 'leaf':
            leaf = expr.leaf
            if leaf.op == 'true':
                return
            n = slot_needs.setdefault(leaf.slot, LaneNeeds())
            n.merge(_leaf_needs(leaf.op, leaf.operand))
            return
        if expr.kind == 'cond':
            check = expr.cond
            if check.value_gather is not None:
                n = elem_needs.setdefault(check.value_gather, LaneNeeds())
                n.merge(_cond_b_needs(check))
                return
            from .ir import ElemGather
            table = elem_needs if isinstance(check.gather, ElemGather) \
                else gather_needs
            n = table.setdefault(check.gather, LaneNeeds())
            n.merge(_cond_needs(check))
            return
        if expr.kind in ('any_elem', 'all_elem') and expr.slot is not None:
            array_paths.add(expr.slot.path)
        for c in expr.children:
            visit_bool(c)

    def visit_status(node: StatusExpr):
        if node is None:
            return
        visit_bool(node.expr)
        if node.kind == 'foreach':
            for entry in node.operand or ():
                if entry.precond is not None:
                    visit_bool(entry.precond)
                visit_bool(entry.deny)
        if node.kind in ('forall', 'exists', 'scalars') and \
                node.slot is not None:
            array_paths.add(node.slot.path)
        if node.sub is not None:
            visit_status(node.sub)
        for c in node.children:
            visit_status(c)

    for prog in cps.programs:
        visit_status(prog.status)
        # trackfail guards reduce element-scoped presence tests over the
        # containers along the slot path — those need count/overflow too
        def visit_guards(node: StatusExpr):
            if node is None:
                return
            if node.kind == 'trackfail' and node.expr is not None:
                def leaf_paths(e):
                    if e.kind == 'leaf' and e.leaf.slot.elem:
                        path = e.leaf.slot.path
                        for i, p in enumerate(path):
                            if p == '*':
                                array_paths.add(path[:i])
                    for c in e.children:
                        leaf_paths(c)
                leaf_paths(node.expr)
            if node.sub is not None:
                visit_guards(node.sub)
            for c in node.children:
                visit_guards(c)
        visit_guards(prog.status)
    # deterministic order shared by the encoder and the evaluator
    return slot_needs, gather_needs, elem_needs, sorted(array_paths)


# ---------------------------------------------------------------------------

def _walk(doc: Any, path: Tuple[str, ...]):
    cur = doc
    for key in path:
        if isinstance(cur, dict):
            if key.startswith('\x00'):
                # wildcard pattern-key segment (compile.WILD_KEY_MARK):
                # descend into the FIRST key matching the pattern, in
                # document order — mirrors ExpandInMetadata's
                # first-match rewrite (validate_pattern.py:202)
                pat = key[4:]
                for rk in cur:
                    if _wild_match(pat, str(rk)):
                        cur = cur[rk]
                        break
                else:
                    return _MISSING
                continue
            if key not in cur:
                return _MISSING
            cur = cur[key]
        else:
            return _MISSING
    return cur


class Batch:
    def __init__(self, n: int, row_count: Optional[int] = None):
        self.n = n
        #: live rows; rows [row_count, n) are canonical-capacity padding
        self.row_count = n if row_count is None else row_count
        #: set when the batch came from a LaneArena pool (recycle key)
        self.arena_key: Optional[tuple] = None
        self.slot_lanes: Dict[Slot, Lanes] = {}
        self.array_meta: Dict[Tuple[str, ...], Dict[str, np.ndarray]] = {}
        self.gather_lanes: Dict[GatherSlot, Lanes] = {}
        self.gather_meta: Dict[GatherSlot, Dict[str, np.ndarray]] = {}
        self.elem_lanes: Dict[Any, Lanes] = {}
        self.elem_meta: Dict[Any, Dict[str, np.ndarray]] = {}

    def clear(self) -> None:
        """Zero every tensor in place for arena reuse."""
        for lanes in self.slot_lanes.values():
            lanes.clear()
        for lanes in self.gather_lanes.values():
            lanes.clear()
        for lanes in self.elem_lanes.values():
            lanes.clear()
        for meta in self.array_meta.values():
            for arr in meta.values():
                arr.fill(0)
        for meta in self.gather_meta.values():
            for arr in meta.values():
                arr.fill(0)
        for meta in self.elem_meta.values():
            for arr in meta.values():
                arr.fill(0)

    def tensors(self) -> Dict[str, np.ndarray]:
        # the row-validity lane rides with every batch: the ragged
        # evaluator masks the capacity-padding tail rows inside the
        # jitted program (cross-row reductions — the mesh verdict
        # summary, the compact fail-detail selection — must never read
        # them), so one compiled capacity serves every occupancy
        out: Dict[str, np.ndarray] = {
            '__rowvalid__':
                (np.arange(self.n) < self.row_count).astype(np.int8)}
        for i, (slot, lanes) in enumerate(self.slot_lanes.items()):
            out.update(lanes.tensors(f's{i}'))
        for j, (path, meta) in enumerate(self.array_meta.items()):
            out[f'a{j}_count'] = meta['count']
            out[f'a{j}_overflow'] = meta['overflow']
            out[f'a{j}_tag'] = meta['tag']
        for k, (g, lanes) in enumerate(self.gather_lanes.items()):
            out.update(lanes.tensors(f'g{k}'))
            meta = self.gather_meta[g]
            out[f'g{k}_kind'] = meta['kind']
            out[f'g{k}_count'] = meta['count']
            out[f'g{k}_overflow'] = meta['overflow']
            out[f'g{k}_notfound'] = meta['notfound']
        for k, (g, lanes) in enumerate(self.elem_lanes.items()):
            out.update(lanes.tensors(f'e{k}'))
            meta = self.elem_meta[g]
            out[f'e{k}_kind'] = meta['kind']
            out[f'e{k}_count'] = meta['count']
            out[f'e{k}_overflow'] = meta['overflow']
            out[f'e{k}_notfound'] = meta['notfound']
        return out


def _pow2_clamp(v: int, lo: int, hi: int) -> int:
    v = max(v, 1)
    return max(lo, min(hi, 1 << (v - 1).bit_length()))


def _container_paths(cps: CompiledPolicySet, array_paths) -> List[Tuple]:
    """All '*'-container prefixes referenced by slots or array nodes."""
    out = set()
    for slot in cps.slots:
        for i, p in enumerate(slot.path):
            if p == '*':
                out.add(slot.path[:i])
    for path in array_paths:
        for i, p in enumerate(path):
            if p == '*':
                out.add(path[:i])
        out.add(path)
    return sorted(out)


def _measure_elems(resources: List[dict], containers: List[Tuple]) -> int:
    """Longest list under any container path (for the element width)."""
    longest = 1
    for doc in resources:
        for path in containers:
            if '*' in path:
                star = path.index('*')
                outer = _walk(doc, path[:star])
                if not isinstance(outer, list):
                    continue
                rest = path[star + 1:]
                for elem in outer[:MAX_ELEMS]:
                    v = _walk(elem, rest) if isinstance(elem, dict) else None
                    if isinstance(v, list):
                        longest = max(longest, len(v))
            else:
                v = _walk(doc, path)
                if isinstance(v, list):
                    longest = max(longest, len(v))
    return longest


def _has_null_dict_value(v) -> bool:
    """True when RFC-7386 merging would change ``v`` — i.e. some dict
    reachable through dicts has a None value (merge_patch does not
    descend into lists)."""
    if isinstance(v, dict):
        for x in v.values():
            if x is None or _has_null_dict_value(x):
                return True
    return False


def encode_batch(resources: List[dict], cps: CompiledPolicySet,
                 padded_n: int = 0,
                 contexts: Optional[List[dict]] = None,
                 arena: Optional[LaneArena] = None) -> Batch:
    """``contexts`` overrides the per-resource gather context (admission
    scans thread operation/userInfo/oldObject through; defaults to the
    background-scan context {'request': {'object': doc}}).

    ``padded_n`` is a *capacity*: rows [len(resources), padded_n) stay
    all-TAG_MISSING and are marked invalid on the ``__rowvalid__`` lane
    (callers draw it from the canonical shape table —
    ``compiler/shapes.py`` — so XLA only ever sees those shapes).

    ``arena`` recycles lane tensors across chunks and keeps the
    cross-chunk value palettes (columnar dictionary encoding); without
    one, an ephemeral arena serves this call only.  Encoding is
    column-major throughout: per-slot value columns are extracted with
    one dict-walk pass, dictionary-encoded, and scattered into the
    preallocated lanes — no per-row intermediate dicts or per-cell
    numpy writes on the hot path."""
    n = max(len(resources), padded_n)
    n_rows = len(resources)
    slot_needs, gather_needs, elem_needs, array_paths = _needs_cached(cps)
    pooled = arena is not None
    if arena is None:
        arena = LaneArena()

    # element width: sized to the longest observed list (pow-2 clamped) —
    # real batches rarely approach MAX_ELEMS, and the element axis
    # multiplies every element-scoped lane's bytes
    containers = _container_paths(cps, array_paths)
    elems = _pow2_clamp(_measure_elems(resources, containers), 4, MAX_ELEMS)

    # gather projections are evaluated against the same RFC-7386
    # merge-patched context the host Context builds (null-valued map keys
    # stripped; engine/context.py:36 merge_patch) — a variable resolving
    # to an explicit null must raise NotFound exactly like the host.
    # Background scans reuse ONE shared context dict across rows (its
    # inner request.object is repointed per row), so the hot path builds
    # no per-row context dicts.
    from ..engine.context import merge_patch

    def _merged(doc: dict) -> dict:
        # merge_patch only rewrites dicts (lists pass by reference), so
        # a doc with no null dict values merges to an equal structure —
        # skip the rebuild, which otherwise dominates context setup
        return merge_patch({}, doc) if _has_null_dict_value(doc) else doc

    searchers = [(g, _gather_searcher(g)) for g in cps.gathers]
    gather_results: Dict[GatherSlot, list] = \
        {g: [None] * n_rows for g in cps.gathers}
    bases: Optional[List[dict]] = None
    if searchers or cps.elem_gathers:
        if contexts is not None:
            bases = [_merged(c) for c in contexts]
        else:
            shared_inner: Dict[str, Any] = {'object': None}
            shared_ctx = {'request': shared_inner}
        for r in range(n_rows):
            if bases is not None:
                ctx = bases[r]
            else:
                shared_inner['object'] = _merged(resources[r])
                ctx = shared_ctx
            for g, searcher in searchers:
                gather_results[g][r] = _run_gather_ctx(searcher, ctx)
    longest_g = 1
    for results in gather_results.values():
        for marker, value in results:
            if marker == 'list':
                longest_g = max(longest_g, len(value))
    gwidth = _pow2_clamp(longest_g, 4, MAX_GATHER)

    # foreach element gathers: evaluate each expr per element of its list
    # (reusing the list gather's results) under the element context the
    # host injects (engine/context.py:109 add_element)
    elem_results: Dict[Any, List[List[Tuple[str, Any]]]] = {}
    longest_eg = 1
    # background scans reuse one shared base context across rows here
    # too (its inner request.object repoints per row)
    eshared_inner: Dict[str, Any] = {'object': None}
    eshared_ctx = {'request': eshared_inner}
    for eg in cps.elem_gathers:
        searcher = _gather_searcher(GatherSlot(eg.expr))
        lres = gather_results.get(GatherSlot(eg.list_expr))
        per_resource: List[List[Tuple[str, Any]]] = []
        for r in range(n_rows):
            marker, value = lres[r]
            if marker == 'list':
                elements = value
            elif marker == 'scalar':
                elements = [value]
            else:
                per_resource.append([])
                continue
            if bases is not None:
                base = bases[r]
            else:
                eshared_inner['object'] = _merged(resources[r])
                base = eshared_ctx
            row: List[Tuple[str, Any]] = []
            for fe, elem in enumerate(elements[:gwidth]):
                if elem is None:
                    row.append(('null', None))
                    continue
                # element context merges over the base like the host's
                # add_element (context.py:109) — nulls stripped again;
                # the merge only rewrites the element subtree, so build
                # the top level directly and strip just the element
                # ktpu: noqa[KTPU205] -- merge_patch needs a fresh
                # accumulator; only elements carrying explicit nulls
                # (rare) take this branch
                stripped = merge_patch({}, elem) \
                    if _has_null_dict_value(elem) else elem
                # ktpu: noqa[KTPU205] -- the per-element context IS the
                # engine's add_element semantics (one injected context
                # per foreach element); foreach gathers are off the
                # streaming fast path
                ctx = {**base,
                       'element': stripped, 'element0': stripped,
                       'elementIndex': fe, 'elementIndex0': fe}
                m2, v2 = _run_gather_ctx(searcher, ctx)
                if m2 == 'list':
                    longest_eg = max(longest_eg, len(v2))
                row.append((m2, v2))
            per_resource.append(row)
        elem_results[eg] = per_resource
    egwidth = _pow2_clamp(longest_eg, 4, MAX_GATHER)

    key = (n, elems, gwidth, egwidth)
    batch = arena.acquire(key) if pooled else None
    if batch is None:
        batch = _build_batch(cps, n, elems, gwidth, egwidth, slot_needs,
                             gather_needs, elem_needs, array_paths)
        if pooled:
            batch.arena_key = key
    else:
        batch.clear()
    batch.row_count = n_rows
    batch.elems = elems
    batch.gather_width = gwidth
    batch.elem_gather_width = egwidth

    plan0, groups, metas = _slot_plan_cached(cps)

    # array metadata channels (count/overflow/tag), column-wise
    for full, prefix, rest in metas:
        meta = batch.array_meta[full]
        if rest is None:
            vals = [_walk(doc, prefix) for doc in resources]
            _set_array_meta_column(meta, None, vals, elems)
        else:
            r_idx: List[int] = []
            e_idx: List[int] = []
            vals = []
            for r, doc in enumerate(resources):
                container = _walk(doc, prefix)
                if not isinstance(container, list):
                    continue
                for e, elem in enumerate(container[:elems]):
                    r_idx.append(r)
                    e_idx.append(e)
                    vals.append(_walk(elem, rest)
                                if isinstance(elem, dict) else _MISSING)
            if vals:
                _set_array_meta_column(
                    meta, (np.asarray(r_idx, np.intp),
                           np.asarray(e_idx, np.intp)), vals, elems)

    # scalar slots: one value column per slot
    for path, slot in plan0:
        lanes = batch.slot_lanes[slot]
        vals = [_walk(doc, path) for doc in resources]
        lanes.encode_column(None, vals,
                            arena.palette(('s', slot), lanes.needs, False))

    # element slots: each container (and each element) is visited once
    # for all the slots under it; values land in per-slot columns
    for prefix, g in groups.items():
        d1, d2 = g['d1'], g['d2']
        cols1 = [([], [], []) for _ in d1]
        # ktpu: noqa[KTPU205] -- one accumulator dict per container
        # GROUP (a handful per policy set), not per row
        cols2 = {mk: [([], [], [], []) for _ in members]
                 for mk, members in d2.items()}
        for r, doc in enumerate(resources):
            container = _walk(doc, prefix)
            if not isinstance(container, list):
                continue  # lanes stay TAG_MISSING; array guards handle it
            for e, elem in enumerate(container[:elems]):
                is_map = isinstance(elem, dict)
                for si, (rest1, _slot) in enumerate(d1):
                    rr, ee, vv = cols1[si]
                    rr.append(r)
                    ee.append(e)
                    if not rest1:
                        vv.append(elem)
                    else:
                        vv.append(_walk(elem, rest1)
                                  if is_map else _MISSING)
                for mk, members in d2.items():
                    inner = _walk(elem, mk) if is_map else _MISSING
                    if not isinstance(inner, list):
                        continue
                    mcols = cols2[mk]
                    for e2, elem2 in enumerate(inner[:elems]):
                        inner_map = isinstance(elem2, dict)
                        for sj, (rest2, _slot2) in enumerate(members):
                            rr, ee, e2l, vv = mcols[sj]
                            rr.append(r)
                            ee.append(e)
                            e2l.append(e2)
                            if not rest2:
                                vv.append(elem2)
                            else:
                                vv.append(_walk(elem2, rest2)
                                          if inner_map else _MISSING)
        for si, (rest1, slot) in enumerate(d1):
            rr, ee, vv = cols1[si]
            if vv:
                lanes = batch.slot_lanes[slot]
                lanes.encode_column(
                    (np.asarray(rr, np.intp), np.asarray(ee, np.intp)),
                    vv, arena.palette(('s', slot), lanes.needs, False))
        for mk, members in d2.items():
            for sj, (rest2, slot2) in enumerate(members):
                rr, ee, e2l, vv = cols2[mk][sj]
                if vv:
                    lanes = batch.slot_lanes[slot2]
                    lanes.encode_column(
                        (np.asarray(rr, np.intp), np.asarray(ee, np.intp),
                         np.asarray(e2l, np.intp)),
                        vv, arena.palette(('s', slot2), lanes.needs,
                                          False))

    for g in cps.gathers:
        lanes, meta = batch.gather_lanes[g], batch.gather_meta[g]
        _fill_gather_column(gather_results[g], lanes, meta, gwidth,
                            arena.palette(('g', g), lanes.needs, True))
    for eg in cps.elem_gathers:
        lanes, meta = batch.elem_lanes[eg], batch.elem_meta[eg]
        _fill_elem_gather_column(
            elem_results[eg], lanes, meta, egwidth,
            arena.palette(('e', eg), lanes.needs, True))
    return batch


def _build_batch(cps: CompiledPolicySet, n: int, elems: int, gwidth: int,
                 egwidth: int, slot_needs, gather_needs, elem_needs,
                 array_paths) -> Batch:
    """Allocate the full lane tensor set for one batch shape (reused
    across chunks via the LaneArena)."""
    batch = Batch(n)
    for path in array_paths:
        depth = sum(1 for p in path if p == '*')
        shape = (n,) + (elems,) * depth
        # ktpu: noqa[KTPU205] -- per-SLOT lane allocation (runs once per
        # batch shape, then recycles through the arena), not per row
        batch.array_meta[path] = {
            'count': np.zeros(shape, np.int32),
            'overflow': np.zeros(shape, bool),
            'tag': np.zeros(shape, np.int8),
        }
    for slot in cps.slots:
        shape = (n,) + (elems,) * slot.depth
        batch.slot_lanes[slot] = Lanes(shape, slot_needs[slot])
    for g in cps.gathers:
        batch.gather_lanes[g] = Lanes((n, gwidth), gather_needs[g])
        # ktpu: noqa[KTPU205] -- per-GATHER metadata allocation (arena-
        # recycled), not per row
        batch.gather_meta[g] = {
            'kind': np.zeros(n, np.int8),
            'count': np.zeros(n, np.int32),
            'overflow': np.zeros(n, bool),
            'notfound': np.zeros(n, bool),
        }
    for eg in cps.elem_gathers:
        batch.elem_lanes[eg] = Lanes((n, gwidth, egwidth), elem_needs[eg])
        # ktpu: noqa[KTPU205] -- per-GATHER metadata allocation (arena-
        # recycled), not per row
        batch.elem_meta[eg] = {
            'kind': np.zeros((n, gwidth), np.int8),
            'count': np.zeros((n, gwidth), np.int32),
            'overflow': np.zeros((n, gwidth), bool),
            'notfound': np.zeros((n, gwidth), bool),
        }
    return batch


def _needs_cached(cps: CompiledPolicySet):
    cached = getattr(cps, '_needs_cache', None)
    if cached is None:
        cached = _analyze_needs(cps)
        cps._needs_cache = cached
    return cached


def _slot_plan_cached(cps: CompiledPolicySet):
    """Precomputed walk plan (batch-independent, cached on the cps):
    scalar slots as flat (path, slot) pairs; element slots grouped by
    container prefix so each array (and each element) is visited once
    for all the slots under it; array-meta paths split into
    (full path, prefix, rest)."""
    cached = getattr(cps, '_slot_plan_cache', None)
    if cached is not None:
        return cached
    plan0 = []
    groups: Dict[Tuple[str, ...], dict] = {}
    for slot in cps.slots:
        d = slot.depth
        if d == 0:
            plan0.append((slot.path, slot))
            continue
        star1 = slot.path.index('*')
        prefix, rest1 = slot.path[:star1], slot.path[star1 + 1:]
        # ktpu: noqa[KTPU205] -- walk-plan construction, cached on the
        # cps: runs once per policy set, never per row
        g = groups.setdefault(prefix, {'d1': [], 'd2': {}})
        if d == 1:
            g['d1'].append((rest1, slot))
        else:
            star2 = rest1.index('*')
            g['d2'].setdefault(rest1[:star2], []).append(
                (rest1[star2 + 1:], slot))
    _needs = _needs_cached(cps)
    metas = []
    for path in _needs[3]:
        if '*' in path:
            star1 = path.index('*')
            metas.append((path, path[:star1], path[star1 + 1:]))
        else:
            metas.append((path, path, None))
    cached = (plan0, groups, metas)
    cps._slot_plan_cache = cached
    return cached


def _set_array_meta_column(meta, idx, values: list, elems: int) -> None:
    """Vectorized array-metadata fill for one column of walked values."""
    m = len(values)
    tag = np.zeros(m, np.int8)
    count = np.zeros(m, np.int32)
    ovf = np.zeros(m, bool)
    for i, value in enumerate(values):
        if value is _MISSING:
            tag[i] = TAG_MISSING
        elif isinstance(value, list):
            tag[i] = TAG_ARRAY
            count[i] = min(len(value), elems)
            ovf[i] = len(value) > elems
        elif value is None:
            tag[i] = TAG_NULL
        elif isinstance(value, dict):
            tag[i] = TAG_MAP
        else:
            tag[i] = TAG_STRING  # non-array scalar: guards only
    if idx is None:
        meta['tag'][:m] = tag
        meta['count'][:m] = count
        meta['overflow'][:m] = ovf
    else:
        meta['tag'][idx] = tag
        meta['count'][idx] = count
        meta['overflow'][idx] = ovf


def _gather_searcher(g: GatherSlot):
    if g.expr.startswith('__pss:'):
        from .pss_compile import virtual_searcher
        return virtual_searcher(g.expr)
    from ..engine.jmespath import compile as jp_compile
    compiled = jp_compile(g.expr)
    return compiled


def _run_gather(searcher, doc: dict):
    """Evaluate one gather projection; returns a (marker, value) pair."""
    return _run_gather_ctx(searcher, {'request': {'object': doc}})


def _run_gather_ctx(searcher, ctx: dict):
    from ..engine.jmespath import NotFoundError
    try:
        result = searcher.search(ctx)
    except NotFoundError:
        # missing path → the host's deterministic substitution-error ERROR
        # (engine.py:388; synthesized on device via STATUS_VAR_ERR)
        return 'notfound', None
    except Exception:  # noqa: BLE001 - interpreter error → host decides
        return 'raised', None
    if result is None:
        return 'null', None
    if isinstance(result, list):
        return 'list', result
    return 'scalar', result


def _fill_gather_column(results: list, lanes: Lanes, meta, gwidth: int,
                        palette: _Palette) -> None:
    """Columnar fill of one gather's whole result column: metadata
    channels batch into single vectorized writes, element values flow
    through the palette encoder."""
    r_idx: List[int] = []
    e_idx: List[int] = []
    vals: list = []
    nf: List[int] = []
    ovf: List[int] = []
    kind1: List[int] = []
    kind2: List[int] = []
    counts: List[int] = []
    for r, (marker, value) in enumerate(results):
        if marker == 'notfound':
            nf.append(r)
            continue
        if marker == 'raised':
            ovf.append(r)
            continue
        if marker == 'null':
            continue
        if marker == 'list':
            kind2.append(r)
            counts.append(min(len(value), gwidth))
            if len(value) > gwidth:
                ovf.append(r)
            for e, v in enumerate(value[:gwidth]):
                r_idx.append(r)
                e_idx.append(e)
                vals.append(v)
            continue
        kind1.append(r)
        r_idx.append(r)
        e_idx.append(0)
        vals.append(value)
    if nf:
        meta['notfound'][np.asarray(nf, np.intp)] = True
    if ovf:
        meta['overflow'][np.asarray(ovf, np.intp)] = True
    if kind1:
        k1 = np.asarray(kind1, np.intp)
        meta['kind'][k1] = 1
        meta['count'][k1] = 1
    if kind2:
        k2 = np.asarray(kind2, np.intp)
        meta['kind'][k2] = 2
        meta['count'][k2] = np.asarray(counts, np.int32)
    if vals:
        lanes.encode_column(
            (np.asarray(r_idx, np.intp), np.asarray(e_idx, np.intp)),
            vals, palette)


def _fill_elem_gather_column(rows: list, lanes: Lanes, meta, egwidth: int,
                             palette: _Palette) -> None:
    """Columnar fill for a per-foreach-element gather: same channels as
    :func:`_fill_gather_column` with a (row, foreach-element) leading
    index."""
    r_idx: List[int] = []
    f_idx: List[int] = []
    e_idx: List[int] = []
    vals: list = []
    nf: List[Tuple[int, int]] = []
    ovf: List[Tuple[int, int]] = []
    kind1: List[Tuple[int, int]] = []
    kind2: List[Tuple[int, int]] = []
    counts: List[int] = []
    for r, row in enumerate(rows):
        for fe, (marker, value) in enumerate(row):
            if marker == 'null':
                continue  # null foreach elements are skipped entirely
            if marker == 'notfound':
                nf.append((r, fe))
                continue
            if marker == 'raised':
                ovf.append((r, fe))
                continue
            if marker == 'list':
                kind2.append((r, fe))
                counts.append(min(len(value), egwidth))
                if len(value) > egwidth:
                    ovf.append((r, fe))
                for e, v in enumerate(value[:egwidth]):
                    r_idx.append(r)
                    f_idx.append(fe)
                    e_idx.append(e)
                    vals.append(v)
                continue
            kind1.append((r, fe))
            r_idx.append(r)
            f_idx.append(fe)
            e_idx.append(0)
            vals.append(value)

    def _ix(pairs):
        a = np.asarray(pairs, np.intp).reshape(-1, 2)
        return a[:, 0], a[:, 1]

    if nf:
        meta['notfound'][_ix(nf)] = True
    if ovf:
        meta['overflow'][_ix(ovf)] = True
    if kind1:
        k1 = _ix(kind1)
        meta['kind'][k1] = 1
        meta['count'][k1] = 1
    if kind2:
        k2 = _ix(kind2)
        meta['kind'][k2] = 2
        meta['count'][k2] = np.asarray(counts, np.int32)
    if vals:
        lanes.encode_column(
            (np.asarray(r_idx, np.intp), np.asarray(f_idx, np.intp),
             np.asarray(e_idx, np.intp)),
            vals, palette)
