"""Batch encoder v2: resources → fixed-shape slot + gather tensors.

Projects each resource onto the compiled slot table and evaluates gather
expressions with the in-repo JMESPath interpreter (the document itself
never reaches the device).  Encoding is conservative toward UNKNOWN: any
value the encoder cannot represent exactly sets flags that make the
device evaluator emit STATUS_HOST, after which the host engine re-runs
that (resource, rule) pair — correctness is never lost.

Lane schema (shared by slots and gather elements; shapes are [R],
[R, E], [R, E, E2] for slots by star-depth, [R, G] for gathers):
  tag        i8   type tag (ir.TAG_*)
  milli      i64  numeric value ×1000 (ints exact; quantity strings)
  milli_ok   bool milli lane is exact
  nanos      i64  Go duration in ns (strings with units)
  nanos_ok   bool
  str_is_int / str_is_float / str_is_qty / str_is_dur   bool
  has_wild   bool value's string form contains * or ? (gathers only)
  str_len    i32  byte length of the value's string form
  str_head   u8[STR_LEN]  first bytes
  str_tail   u8[TAIL_LEN] last bytes, right-aligned
Array nodes referenced by forall/exists additionally get, keyed by path:
  count      i32  number of elements (clamped to MAX_ELEMS)
  overflow   bool more than MAX_ELEMS elements → device UNKNOWN
Gathers additionally get:
  kind       i8   0 = null/absent, 1 = scalar, 2 = list
  count      i32
  overflow   bool
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from fractions import Fraction
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.duration import parse_duration
from ..utils.quantity import Quantity
from ..utils.wildcard import match as _wild_match
from .ir import (MAX_ELEMS, MAX_GATHER, STR_LEN, TAG_ARRAY, TAG_BOOL,
                 TAG_FLOAT, TAG_INT, TAG_MAP, TAG_MISSING, TAG_NULL,
                 TAG_STRING, TAIL_LEN, CompiledPolicySet, GatherSlot, Slot,
                 StatusExpr)

_INT64_MAX = (1 << 63) - 1

_MISSING = object()

# Per-slot/gather lane requirements, computed from exactly the ops the
# evaluator performs against it (ops/eval.py read-set).  ``head`` is the
# byte width of the string-head window — sized to the longest constant a
# comparison needs, not a fixed 64 — which is the dominant memory/transfer
# term of the encoded batch.
@dataclass
class LaneNeeds:
    head: int = 0
    tail: bool = False
    length: bool = False
    milli: bool = False
    nanos: bool = False
    wild: bool = False
    lit_zero: bool = False

    def merge(self, other: 'LaneNeeds') -> None:
        self.head = max(self.head, other.head)
        self.tail = self.tail or other.tail
        self.length = self.length or other.length
        self.milli = self.milli or other.milli
        self.nanos = self.nanos or other.nanos
        self.wild = self.wild or other.wild
        self.lit_zero = self.lit_zero or other.lit_zero

    def add_pattern(self, pattern: str) -> None:
        """Lanes read by a constant glob comparison (ir.classify_wildcard
        keeps this in sync with eval._View.match_const_pattern)."""
        from .ir import classify_wildcard
        kind, parts = classify_wildcard(pattern)
        if kind == 'eq':
            self.head = max(self.head, len(parts[0].encode('utf-8')))
            self.length = True
        elif kind == 'nonempty':
            self.length = True
        elif kind == 'prefix':
            self.head = max(self.head, len(parts[0].encode('utf-8')))
            self.length = True
        elif kind == 'suffix':
            self.tail = True
            self.length = True
        elif kind == 'prefix_suffix':
            self.head = max(self.head, len(parts[0].encode('utf-8')))
            self.tail = True
            self.length = True
        elif kind == 'dp':
            self.head = STR_LEN
            self.length = True
        # 'any' reads only the tag


def _go_float_str(v: float) -> str:
    from ..engine.pattern import _go_format_float_e
    return _go_format_float_e(v)


def _sprint(v: Any) -> str:
    """Go fmt.Sprint for scalars (operators.py:_sprint)."""
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e21:
            return str(int(v))
        return repr(v)
    return str(v)


class Lanes:
    """numpy lane arrays for one slot or gather at a given shape, sized to
    exactly the lanes (and head byte width) its comparisons read."""

    def __init__(self, shape: Tuple[int, ...], needs: LaneNeeds):
        self.needs = needs
        self.tag = np.zeros(shape, np.int8)
        z64 = lambda: np.zeros(shape, np.int64)  # noqa: E731
        zb = lambda: np.zeros(shape, bool)       # noqa: E731
        self.milli = z64() if needs.milli else None
        self.milli_ok = zb() if needs.milli else None
        self.nanos = z64() if needs.nanos else None
        self.nanos_ok = zb() if needs.nanos else None
        # the string-parse flags ride with the numeric bundle that gates
        # on them (eq_int/str_is_qty read milli; str_is_dur reads nanos)
        self.str_is_int = zb() if needs.milli else None
        self.str_is_float = zb() if needs.milli else None
        self.str_is_qty = zb() if needs.milli else None
        self.str_is_dur = zb() if needs.nanos else None
        self.lit_zero = zb() if needs.lit_zero else None
        if needs.length or needs.head or needs.tail:
            self.str_len = np.zeros(shape, np.int32)
        else:
            self.str_len = None
        if needs.head:
            # round the head window up for alignment / fewer pack groups
            w = min(STR_LEN, (needs.head + 7) & ~7)
            self.str_head = np.zeros(shape + (w,), np.uint8)
        else:
            self.str_head = None
        self.str_tail = np.zeros(shape + (TAIL_LEN,), np.uint8) \
            if needs.tail else None
        self.has_wild = zb() if needs.wild else None

    _LANE_NAMES = ('tag', 'milli', 'milli_ok', 'nanos', 'nanos_ok',
                   'str_is_int', 'str_is_float', 'str_is_qty', 'str_is_dur',
                   'lit_zero', 'str_len', 'str_head', 'str_tail', 'has_wild')

    def tensors(self, prefix: str) -> Dict[str, np.ndarray]:
        out = {}
        for name in self._LANE_NAMES:
            v = getattr(self, name)
            if v is not None:
                out[f'{prefix}_{name}'] = v
        return out

    # -- value encoding ------------------------------------------------------

    def encode(self, idx, value: Any, string_form: Optional[str] = None,
               sprint_form: bool = False) -> None:
        """Encode one scalar value at ``idx``.

        ``sprint_form`` selects the operators' Go string form (gathers)
        over the pattern walk's float formatting (slots).
        """
        if value is _MISSING:
            self.tag[idx] = TAG_MISSING
            return
        if value is None:
            self.tag[idx] = TAG_NULL
            if self.milli is not None:
                self.milli_ok[idx] = True
            if self.nanos is not None:
                self.nanos_ok[idx] = True
            return
        if isinstance(value, bool):
            self.tag[idx] = TAG_BOOL
            if self.milli is not None:
                self.milli[idx] = 1000 if value else 0
                self.milli_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(idx, 'true' if value else 'false')
            return
        if isinstance(value, int):
            self.tag[idx] = TAG_INT
            if self.milli is not None and abs(value) <= _INT64_MAX // 1000:
                self.milli[idx] = value * 1000
                self.milli_ok[idx] = True
            if self.nanos is not None and value == 0:
                # _number_to_string(0) == '0' parses as Go duration 0
                self.nanos_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(idx, str(value))
            if self.str_is_int is not None:
                self.str_is_int[idx] = True
                self.str_is_float[idx] = True
            return
        if isinstance(value, float):
            self.tag[idx] = TAG_FLOAT
            if self.milli is not None and math.isfinite(value):
                frac = Fraction(str(value)) * 1000
                if frac.denominator == 1 and abs(frac.numerator) <= _INT64_MAX:
                    self.milli[idx] = int(frac)
                    self.milli_ok[idx] = True
            if self.str_len is not None:
                self._encode_str(
                    idx, _sprint(value) if sprint_form
                    else _go_float_str(value))
            if self.str_is_float is not None:
                self.str_is_float[idx] = True
            return
        if isinstance(value, str):
            self.tag[idx] = TAG_STRING
            if self.str_len is not None:
                self._encode_str(idx, value)
            if self.lit_zero is not None and value == '0':
                self.lit_zero[idx] = True
            if self.str_is_int is not None:
                try:
                    int(value, 10)
                    self.str_is_int[idx] = True
                    self.str_is_float[idx] = True
                except ValueError:
                    try:
                        float(value)
                        self.str_is_float[idx] = True
                    except ValueError:
                        pass
            if self.has_wild is not None:
                self.has_wild[idx] = ('*' in value) or ('?' in value)
            if self.milli is not None:
                try:
                    q = Quantity.parse(value)
                except ValueError:
                    # int()-parseable strings the quantity grammar rejects
                    # (' 5', '5_0') still feed eq_int via the milli lane
                    try:
                        iv = int(value, 10)
                    except ValueError:
                        pass
                    else:
                        if abs(iv) <= _INT64_MAX // 1000:
                            self.milli[idx] = iv * 1000
                            self.milli_ok[idx] = True
                else:
                    if self.str_is_qty is not None:
                        self.str_is_qty[idx] = True
                    m = q.value * 1000
                    if m.denominator == 1 and abs(m.numerator) <= _INT64_MAX:
                        self.milli[idx] = int(m)
                        self.milli_ok[idx] = True
            if self.nanos is not None:
                try:
                    ns = parse_duration(value)
                except ValueError:
                    pass
                else:
                    if self.str_is_dur is not None:
                        self.str_is_dur[idx] = True
                    # str_is_dur without nanos_ok = parsed but out of the
                    # int64 lane → undecidable on device
                    if abs(ns) <= _INT64_MAX:
                        self.nanos[idx] = ns
                        self.nanos_ok[idx] = True
            return
        if isinstance(value, dict):
            self.tag[idx] = TAG_MAP
            return
        if isinstance(value, list):
            self.tag[idx] = TAG_ARRAY
            return
        self.tag[idx] = TAG_MISSING

    def _encode_str(self, idx, s: str) -> None:
        b = s.encode('utf-8')
        self.str_len[idx] = len(b)
        if self.str_head is not None:
            w = self.str_head.shape[-1]
            head = b[:w]
            self.str_head[idx][:len(head)] = np.frombuffer(head, np.uint8)
        if self.str_tail is not None:
            tail = b[-TAIL_LEN:]
            self.str_tail[idx][TAIL_LEN - len(tail):] = \
                np.frombuffer(tail, np.uint8)


# ---------------------------------------------------------------------------
# need analysis: which lanes each slot/gather requires (mirrors the exact
# read-set of ops/eval.py for each leaf op / condition check)

def _blen(s: str) -> int:
    # floor 1: ops that compare against '' still read the str_head lane
    # (eval.py eq_const), so the window must exist even for empty
    # constants
    return min(max(len(s.encode('utf-8')), 1), STR_LEN)


def _leaf_needs(op: str, operand: Any) -> LaneNeeds:
    n = LaneNeeds()
    if op in ('eq_bool', 'eq_int', 'eq_float', 'cmp_qty',
              'is_true', 'is_false', 'is_zero_num'):
        n.milli = True
    if op == 'truthy':
        n.milli = True
        n.length = True
    if op == 'eq_null':
        n.milli = True
        n.length = True
    if op == 'cmp_dur':
        n.nanos = True
    if op in ('eq_str', 'prefix'):
        n.head = _blen(operand)
        n.length = True
    if op == 'suffix':
        n.tail = True
        n.length = True
    if op in ('min_len', 'nonempty'):
        n.length = True
    if op == 'wildcard':
        n.head = STR_LEN
        n.length = True
    return n


_IN_FAMILY = ('in', 'anyin', 'allin', 'notin', 'anynotin', 'allnotin')


def _cond_needs(check) -> LaneNeeds:
    """Gather lanes read by one condition check (ops/eval.py cond_tf)."""
    from ..engine import pattern as leaf_pattern
    n = LaneNeeds()
    op = check.op
    if op in ('equal', 'equals', 'notequal', 'notequals'):
        if check.list_value:
            for cv in check.values:
                if isinstance(cv, str):
                    n.head = max(n.head, _blen(cv))
                    n.length = True
                elif isinstance(cv, (bool, int, float)):
                    n.milli = True
        else:
            v = check.values[0]
            if isinstance(v, bool):
                n.milli = True
            elif isinstance(v, (int, float)):
                n.milli = True
                n.nanos = True
                n.lit_zero = True
            elif isinstance(v, str):
                n.milli = True
                n.nanos = True
                n.lit_zero = True
                n.length = True
                n.head = max(n.head, _blen(v))
                n.add_pattern(v)
    elif op in _IN_FAMILY:
        if check.list_value:
            n.wild = True
            n.length = True
            for cv in check.values:
                vs = cv if isinstance(cv, str) else _sprint(cv)
                n.add_pattern(vs)
                n.head = max(n.head, _blen(vs))
        else:
            v = check.values[0]
            if isinstance(v, str):
                n.length = True
                n.head = max(n.head, _blen(v))
                n.add_pattern(v)
                if leaf_pattern.get_operator_from_string_pattern(v) == \
                        leaf_pattern.OP_IN_RANGE:
                    n.milli = True
                    n.nanos = True
                else:
                    # list keys run _both_dir_member over the parsed
                    # JSON elements (or [v] itself): wildcard matching in
                    # both directions needs has_wild plus the per-element
                    # pattern windows (eval.py _in_family_tf)
                    n.wild = True
                    import json as _json
                    try:
                        arr = _json.loads(v)
                    except ValueError:
                        arr = None
                    elems = [x for x in arr if isinstance(x, str)] \
                        if isinstance(arr, list) else [v]
                    for x in elems:
                        n.head = max(n.head, _blen(x))
                        n.add_pattern(x)
    else:  # numeric comparisons
        n.milli = True
        n.nanos = True
        n.lit_zero = True
    return n


def _cond_b_needs(check) -> LaneNeeds:
    """Value-gather lanes read by a mode-B check (const key vs gather
    value; ops/eval.py _cond_b_tf)."""
    n = LaneNeeds()
    key = check.key_const
    op = check.op
    if op in ('equal', 'equals', 'notequal', 'notequals'):
        if isinstance(key, bool):
            n.milli = True
        elif isinstance(key, (int, float)):
            n.milli = True
        elif isinstance(key, str):
            n.milli = True
            n.nanos = True
            n.lit_zero = True
            n.length = True
            n.wild = True
            n.head = max(n.head, _blen(key))
    else:  # in-family with scalar const key
        ks = key if isinstance(key, str) else _sprint(key)
        n.length = True
        n.wild = True
        # the scalar-value suspicion scan marks values longer than the
        # window as undecidable (host re-run), so a narrow head suffices
        n.head = max(16, _blen(ks))
        n.add_pattern(ks)
    return n


def _analyze_needs(cps: CompiledPolicySet):
    slot_needs: Dict[Slot, LaneNeeds] = {s: LaneNeeds() for s in cps.slots}
    gather_needs: Dict[GatherSlot, LaneNeeds] = \
        {g: LaneNeeds() for g in cps.gathers}
    elem_needs: Dict = {g: LaneNeeds() for g in cps.elem_gathers}
    array_paths: set = set()

    def visit_bool(expr):
        if expr is None:
            return
        if expr.kind == 'leaf':
            leaf = expr.leaf
            if leaf.op == 'true':
                return
            n = slot_needs.setdefault(leaf.slot, LaneNeeds())
            n.merge(_leaf_needs(leaf.op, leaf.operand))
            return
        if expr.kind == 'cond':
            check = expr.cond
            if check.value_gather is not None:
                n = elem_needs.setdefault(check.value_gather, LaneNeeds())
                n.merge(_cond_b_needs(check))
                return
            from .ir import ElemGather
            table = elem_needs if isinstance(check.gather, ElemGather) \
                else gather_needs
            n = table.setdefault(check.gather, LaneNeeds())
            n.merge(_cond_needs(check))
            return
        if expr.kind in ('any_elem', 'all_elem') and expr.slot is not None:
            array_paths.add(expr.slot.path)
        for c in expr.children:
            visit_bool(c)

    def visit_status(node: StatusExpr):
        if node is None:
            return
        visit_bool(node.expr)
        if node.kind == 'foreach':
            for entry in node.operand or ():
                if entry.precond is not None:
                    visit_bool(entry.precond)
                visit_bool(entry.deny)
        if node.kind in ('forall', 'exists', 'scalars') and \
                node.slot is not None:
            array_paths.add(node.slot.path)
        if node.sub is not None:
            visit_status(node.sub)
        for c in node.children:
            visit_status(c)

    for prog in cps.programs:
        visit_status(prog.status)
        # trackfail guards reduce element-scoped presence tests over the
        # containers along the slot path — those need count/overflow too
        def visit_guards(node: StatusExpr):
            if node is None:
                return
            if node.kind == 'trackfail' and node.expr is not None:
                def leaf_paths(e):
                    if e.kind == 'leaf' and e.leaf.slot.elem:
                        path = e.leaf.slot.path
                        for i, p in enumerate(path):
                            if p == '*':
                                array_paths.add(path[:i])
                    for c in e.children:
                        leaf_paths(c)
                leaf_paths(node.expr)
            if node.sub is not None:
                visit_guards(node.sub)
            for c in node.children:
                visit_guards(c)
        visit_guards(prog.status)
    # deterministic order shared by the encoder and the evaluator
    return slot_needs, gather_needs, elem_needs, sorted(array_paths)


# ---------------------------------------------------------------------------

def _walk(doc: Any, path: Tuple[str, ...]):
    cur = doc
    for key in path:
        if isinstance(cur, dict):
            if key.startswith('\x00'):
                # wildcard pattern-key segment (compile.WILD_KEY_MARK):
                # descend into the FIRST key matching the pattern, in
                # document order — mirrors ExpandInMetadata's
                # first-match rewrite (validate_pattern.py:202)
                pat = key[4:]
                for rk in cur:
                    if _wild_match(pat, str(rk)):
                        cur = cur[rk]
                        break
                else:
                    return _MISSING
                continue
            if key not in cur:
                return _MISSING
            cur = cur[key]
        else:
            return _MISSING
    return cur


class Batch:
    def __init__(self, n: int, row_count: Optional[int] = None):
        self.n = n
        #: live rows; rows [row_count, n) are canonical-capacity padding
        self.row_count = n if row_count is None else row_count
        self.slot_lanes: Dict[Slot, Lanes] = {}
        self.array_meta: Dict[Tuple[str, ...], Dict[str, np.ndarray]] = {}
        self.gather_lanes: Dict[GatherSlot, Lanes] = {}
        self.gather_meta: Dict[GatherSlot, Dict[str, np.ndarray]] = {}
        self.elem_lanes: Dict[Any, Lanes] = {}
        self.elem_meta: Dict[Any, Dict[str, np.ndarray]] = {}

    def tensors(self) -> Dict[str, np.ndarray]:
        # the row-validity lane rides with every batch: the ragged
        # evaluator masks the capacity-padding tail rows inside the
        # jitted program (cross-row reductions — the mesh verdict
        # summary, the compact fail-detail selection — must never read
        # them), so one compiled capacity serves every occupancy
        out: Dict[str, np.ndarray] = {
            '__rowvalid__':
                (np.arange(self.n) < self.row_count).astype(np.int8)}
        for i, (slot, lanes) in enumerate(self.slot_lanes.items()):
            out.update(lanes.tensors(f's{i}'))
        for j, (path, meta) in enumerate(self.array_meta.items()):
            out[f'a{j}_count'] = meta['count']
            out[f'a{j}_overflow'] = meta['overflow']
            out[f'a{j}_tag'] = meta['tag']
        for k, (g, lanes) in enumerate(self.gather_lanes.items()):
            out.update(lanes.tensors(f'g{k}'))
            meta = self.gather_meta[g]
            out[f'g{k}_kind'] = meta['kind']
            out[f'g{k}_count'] = meta['count']
            out[f'g{k}_overflow'] = meta['overflow']
            out[f'g{k}_notfound'] = meta['notfound']
        for k, (g, lanes) in enumerate(self.elem_lanes.items()):
            out.update(lanes.tensors(f'e{k}'))
            meta = self.elem_meta[g]
            out[f'e{k}_kind'] = meta['kind']
            out[f'e{k}_count'] = meta['count']
            out[f'e{k}_overflow'] = meta['overflow']
            out[f'e{k}_notfound'] = meta['notfound']
        return out


def _pow2_clamp(v: int, lo: int, hi: int) -> int:
    v = max(v, 1)
    return max(lo, min(hi, 1 << (v - 1).bit_length()))


def _container_paths(cps: CompiledPolicySet, array_paths) -> List[Tuple]:
    """All '*'-container prefixes referenced by slots or array nodes."""
    out = set()
    for slot in cps.slots:
        for i, p in enumerate(slot.path):
            if p == '*':
                out.add(slot.path[:i])
    for path in array_paths:
        for i, p in enumerate(path):
            if p == '*':
                out.add(path[:i])
        out.add(path)
    return sorted(out)


def _measure_elems(resources: List[dict], containers: List[Tuple]) -> int:
    """Longest list under any container path (for the element width)."""
    longest = 1
    for doc in resources:
        for path in containers:
            if '*' in path:
                star = path.index('*')
                outer = _walk(doc, path[:star])
                if not isinstance(outer, list):
                    continue
                rest = path[star + 1:]
                for elem in outer[:MAX_ELEMS]:
                    v = _walk(elem, rest) if isinstance(elem, dict) else None
                    if isinstance(v, list):
                        longest = max(longest, len(v))
            else:
                v = _walk(doc, path)
                if isinstance(v, list):
                    longest = max(longest, len(v))
    return longest


def _has_null_dict_value(v) -> bool:
    """True when RFC-7386 merging would change ``v`` — i.e. some dict
    reachable through dicts has a None value (merge_patch does not
    descend into lists)."""
    if isinstance(v, dict):
        for x in v.values():
            if x is None or _has_null_dict_value(x):
                return True
    return False


def encode_batch(resources: List[dict], cps: CompiledPolicySet,
                 padded_n: int = 0,
                 contexts: Optional[List[dict]] = None) -> Batch:
    """``contexts`` overrides the per-resource gather context (admission
    scans thread operation/userInfo/oldObject through; defaults to the
    background-scan context {'request': {'object': doc}}).

    ``padded_n`` is a *capacity*: rows [len(resources), padded_n) stay
    all-TAG_MISSING and are marked invalid on the ``__rowvalid__`` lane
    (callers draw it from the canonical shape table —
    ``compiler/shapes.py`` — so XLA only ever sees those shapes)."""
    n = max(len(resources), padded_n)
    batch = Batch(n, row_count=len(resources))
    slot_needs, gather_needs, elem_needs, array_paths = _needs_cached(cps)

    # element width: sized to the longest observed list (pow-2 clamped) —
    # real batches rarely approach MAX_ELEMS, and the element axis
    # multiplies every element-scoped lane's bytes
    containers = _container_paths(cps, array_paths)
    elems = _pow2_clamp(_measure_elems(resources, containers), 4, MAX_ELEMS)
    batch.elems = elems

    # gather projections are evaluated against the same RFC-7386
    # merge-patched context the host Context builds (null-valued map keys
    # stripped; engine/context.py:36 merge_patch) — a variable resolving
    # to an explicit null must raise NotFound exactly like the host
    from ..engine.context import merge_patch

    def _merged(doc: dict) -> dict:
        # merge_patch only rewrites dicts (lists pass by reference), so
        # a doc with no null dict values merges to an equal structure —
        # skip the rebuild, which otherwise dominates context setup
        return merge_patch({}, doc) if _has_null_dict_value(doc) else doc

    if contexts is not None:
        bases = [_merged(c) for c in contexts]
    else:
        bases = [{'request': {'object': _merged(doc)}}
                 for doc in resources]
    gather_results = {
        g: [_run_gather_ctx(searcher, base) for base in bases]
        for g, searcher in ((g, _gather_searcher(g)) for g in cps.gathers)}
    longest_g = 1
    for results in gather_results.values():
        for marker, value in results:
            if marker == 'list':
                longest_g = max(longest_g, len(value))
    gwidth = _pow2_clamp(longest_g, 4, MAX_GATHER)
    batch.gather_width = gwidth

    # foreach element gathers: evaluate each expr per element of its list
    # (reusing the list gather's results) under the element context the
    # host injects (engine/context.py:109 add_element)
    elem_results: Dict[Any, List[List[Tuple[str, Any]]]] = {}
    longest_eg = 1
    for eg in cps.elem_gathers:
        searcher = _gather_searcher(GatherSlot(eg.expr))
        lres = gather_results.get(GatherSlot(eg.list_expr))
        per_resource: List[List[Tuple[str, Any]]] = []
        for r, doc in enumerate(resources):
            marker, value = lres[r]
            if marker == 'list':
                elements = value
            elif marker == 'scalar':
                elements = [value]
            else:
                per_resource.append([])
                continue
            row: List[Tuple[str, Any]] = []
            for fe, elem in enumerate(elements[:gwidth]):
                if elem is None:
                    row.append(('null', None))
                    continue
                # element context merges over the base like the host's
                # add_element (context.py:109) — nulls stripped again;
                # the merge only rewrites the element subtree, so build
                # the top level directly and strip just the element
                stripped = merge_patch({}, elem) \
                    if _has_null_dict_value(elem) else elem
                ctx = {**bases[r],
                       'element': stripped, 'element0': stripped,
                       'elementIndex': fe, 'elementIndex0': fe}
                m2, v2 = _run_gather_ctx(searcher, ctx)
                if m2 == 'list':
                    longest_eg = max(longest_eg, len(v2))
                row.append((m2, v2))
            per_resource.append(row)
        elem_results[eg] = per_resource
    egwidth = _pow2_clamp(longest_eg, 4, MAX_GATHER)
    batch.elem_gather_width = egwidth

    # array metadata channels (count/overflow/tag) for forall/exists nodes
    for path in array_paths:
        depth = sum(1 for p in path if p == '*')
        shape = (n,) + (elems,) * depth
        batch.array_meta[path] = {
            'count': np.zeros(shape, np.int32),
            'overflow': np.zeros(shape, bool),
            'tag': np.zeros(shape, np.int8),
        }

    for slot in cps.slots:
        shape = (n,) + (elems,) * slot.depth
        batch.slot_lanes[slot] = Lanes(shape, slot_needs[slot])

    for g in cps.gathers:
        batch.gather_lanes[g] = Lanes((n, gwidth), gather_needs[g])
        batch.gather_meta[g] = {
            'kind': np.zeros(n, np.int8),
            'count': np.zeros(n, np.int32),
            'overflow': np.zeros(n, bool),
            'notfound': np.zeros(n, bool),
        }

    for eg in cps.elem_gathers:
        batch.elem_lanes[eg] = Lanes((n, gwidth, egwidth), elem_needs[eg])
        batch.elem_meta[eg] = {
            'kind': np.zeros((n, gwidth), np.int8),
            'count': np.zeros((n, gwidth), np.int32),
            'overflow': np.zeros((n, gwidth), bool),
            'notfound': np.zeros((n, gwidth), bool),
        }

    slot_plan = _slot_plan(cps, batch)
    for r, doc in enumerate(resources):
        _encode_doc(r, doc, slot_plan, batch, elems)
    for g in cps.gathers:
        lanes, meta = batch.gather_lanes[g], batch.gather_meta[g]
        results = gather_results[g]
        for r, (marker, value) in enumerate(results):
            _fill_gather(r, marker, value, lanes, meta, gwidth)
    for eg in cps.elem_gathers:
        lanes, meta = batch.elem_lanes[eg], batch.elem_meta[eg]
        rows = elem_results[eg]
        for r, row in enumerate(rows):
            for fe, (marker, value) in enumerate(row):
                if marker == 'null':
                    continue  # null foreach elements are skipped entirely
                _fill_gather((r, fe), marker, value, lanes, meta, egwidth)
    return batch


def _needs_cached(cps: CompiledPolicySet):
    cached = getattr(cps, '_needs_cache', None)
    if cached is None:
        cached = _analyze_needs(cps)
        cps._needs_cache = cached
    return cached


def _slot_plan(cps: CompiledPolicySet, batch: Batch):
    """Precomputed walk plan: scalar slots as flat (path, lanes) pairs;
    element slots grouped by container prefix so each array (and each
    element) is visited once for all the slots under it."""
    plan0 = []
    groups: Dict[Tuple[str, ...], dict] = {}
    for slot in cps.slots:
        lanes = batch.slot_lanes[slot]
        d = slot.depth
        if d == 0:
            plan0.append((slot.path, lanes))
            continue
        star1 = slot.path.index('*')
        prefix, rest1 = slot.path[:star1], slot.path[star1 + 1:]
        g = groups.setdefault(prefix, {'d1': [], 'd2': {}})
        if d == 1:
            g['d1'].append((rest1, lanes))
        else:
            star2 = rest1.index('*')
            g['d2'].setdefault(rest1[:star2], []).append(
                (rest1[star2 + 1:], lanes))
    # array-meta walk plan: (path, meta, star1 or None, rest)
    metas = []
    for path, meta in batch.array_meta.items():
        if '*' in path:
            star1 = path.index('*')
            metas.append((path[:star1], meta, path[star1 + 1:]))
        else:
            metas.append((path, meta, None))
    return plan0, groups, metas


def _encode_doc(r: int, doc: dict, slot_plan, batch: Batch,
                elems: int) -> None:
    plan0, groups, metas = slot_plan
    for path, meta, rest in metas:
        if rest is None:
            _set_array_meta(meta, r, _walk(doc, path), elems)
            continue
        container = _walk(doc, path)
        if not isinstance(container, list):
            continue
        for e, elem in enumerate(container[:elems]):
            value = _walk(elem, rest) if isinstance(elem, dict) else _MISSING
            _set_array_meta(meta, (r, e), value, elems)
    for path, lanes in plan0:
        lanes.encode(r, _walk(doc, path))
    for prefix, g in groups.items():
        container = _walk(doc, prefix)
        if not isinstance(container, list):
            continue  # lanes stay TAG_MISSING; array guards handle it
        d1, d2 = g['d1'], g['d2']
        for e, elem in enumerate(container[:elems]):
            re = (r, e)
            is_map = isinstance(elem, dict)
            for rest1, lanes in d1:
                if not rest1:
                    lanes.encode(re, elem)
                else:
                    lanes.encode(
                        re, _walk(elem, rest1) if is_map else _MISSING)
            for mid, members in d2.items():
                inner = _walk(elem, mid) if is_map else _MISSING
                if not isinstance(inner, list):
                    continue
                for e2, elem2 in enumerate(inner[:elems]):
                    ree = (r, e, e2)
                    inner_map = isinstance(elem2, dict)
                    for rest2, lanes in members:
                        if not rest2:
                            lanes.encode(ree, elem2)
                        else:
                            lanes.encode(ree, _walk(elem2, rest2)
                                         if inner_map else _MISSING)


def _set_array_meta(meta, idx, value, elems: int) -> None:
    if value is _MISSING:
        meta['tag'][idx] = TAG_MISSING
    elif isinstance(value, list):
        meta['tag'][idx] = TAG_ARRAY
        meta['count'][idx] = min(len(value), elems)
        meta['overflow'][idx] = len(value) > elems
    elif value is None:
        meta['tag'][idx] = TAG_NULL
    elif isinstance(value, dict):
        meta['tag'][idx] = TAG_MAP
    else:
        meta['tag'][idx] = TAG_STRING  # non-array scalar: guards only


def _gather_searcher(g: GatherSlot):
    if g.expr.startswith('__pss:'):
        from .pss_compile import virtual_searcher
        return virtual_searcher(g.expr)
    from ..engine.jmespath import compile as jp_compile
    compiled = jp_compile(g.expr)
    return compiled


def _run_gather(searcher, doc: dict):
    """Evaluate one gather projection; returns a (marker, value) pair."""
    return _run_gather_ctx(searcher, {'request': {'object': doc}})


def _run_gather_ctx(searcher, ctx: dict):
    from ..engine.jmespath import NotFoundError
    try:
        result = searcher.search(ctx)
    except NotFoundError:
        # missing path → the host's deterministic substitution-error ERROR
        # (engine.py:388; synthesized on device via STATUS_VAR_ERR)
        return 'notfound', None
    except Exception:  # noqa: BLE001 - interpreter error → host decides
        return 'raised', None
    if result is None:
        return 'null', None
    if isinstance(result, list):
        return 'list', result
    return 'scalar', result


def _fill_gather(r, marker: str, value, lanes: Lanes, meta,
                 gwidth: int) -> None:
    """Fill one gather row; ``r`` is an int (plain gathers) or an
    (r, fe) tuple (per-foreach-element gathers)."""
    idx = r if isinstance(r, tuple) else (r,)
    if marker == 'notfound':
        meta['notfound'][idx] = True
        return
    if marker == 'raised':
        meta['overflow'][idx] = True
        return
    if marker == 'null':
        return
    if marker == 'list':
        meta['kind'][idx] = 2
        meta['count'][idx] = min(len(value), gwidth)
        if len(value) > gwidth:
            meta['overflow'][idx] = True
        for e, v in enumerate(value[:gwidth]):
            lanes.encode(idx + (e,), v, sprint_form=True)
        return
    meta['kind'][idx] = 1
    meta['count'][idx] = 1
    lanes.encode(idx + (0,), value, sprint_form=True)
