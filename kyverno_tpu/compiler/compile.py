"""Policy-set compiler: validate rules → vectorized check programs.

Compiles the vectorizable subset (pattern / anyPattern rules over scalar
paths and one array-of-maps level, with conditional / equality / negation /
existence anchors and the full string-operator grammar). Rules outside the
subset — variables, context entries, preconditions, deny, foreach,
podSecurity, nested arrays, metadata wildcards — fall back to the host
engine, preserving exact semantics.

The leaf compilation mirrors the reference's OR-chain coercions
(reference: pkg/engine/pattern/pattern.go:207 validateString tries
duration, then quantity, then wildcard string).
"""

from __future__ import annotations

from fractions import Fraction
from typing import Any, List, Optional, Tuple

from ..api.policy import Policy
from ..autogen.autogen import compute_rules
from ..engine import anchor as anchor_mod
from ..engine import pattern as leaf_pattern
from ..engine.variables import is_reference, is_variable
from ..utils.duration import parse_duration
from ..utils.quantity import Quantity
from .ir import (CMP_EQ, CMP_GE, CMP_GT, CMP_LE, CMP_LT, CMP_NE, MAX_ELEMS,
                 STR_LEN, TAIL_LEN, BoolExpr, CompiledPolicySet, CompileError,
                 ElementBlock, Leaf, RuleProgram, Slot)

_CMP_OF_OP = {
    leaf_pattern.OP_MORE: CMP_GT,
    leaf_pattern.OP_MORE_EQUAL: CMP_GE,
    leaf_pattern.OP_LESS: CMP_LT,
    leaf_pattern.OP_LESS_EQUAL: CMP_LE,
    leaf_pattern.OP_EQUAL: CMP_EQ,
    leaf_pattern.OP_NOT_EQUAL: CMP_NE,
}


def compile_policies(policies: List[Policy]) -> CompiledPolicySet:
    cps = CompiledPolicySet()
    cps.policies = policies
    for p_idx, policy in enumerate(policies):
        for r_idx, rule in enumerate(compute_rules(policy)):
            try:
                program = _compile_rule(cps, policy, p_idx, r_idx, rule)
            except CompileError:
                cps.host_rules.append((p_idx, rule, policy))
                continue
            cps.programs.append(program)
    return cps


def _compile_rule(cps: CompiledPolicySet, policy: Policy, p_idx: int,
                  r_idx: int, rule: dict) -> RuleProgram:
    if not rule.get('validate'):
        raise CompileError('not a validate rule')
    validate = rule['validate']
    if rule.get('context') or rule.get('preconditions'):
        raise CompileError('context/preconditions require the host engine')
    unsupported = [k for k in ('deny', 'foreach', 'podSecurity', 'manifests')
                   if validate.get(k) is not None]
    if unsupported:
        raise CompileError(f'unsupported validate type {unsupported}')
    match = rule.get('match') or {}
    _require_simple_match(match)
    _require_simple_match(rule.get('exclude') or {})

    name = rule.get('name', '')
    if validate.get('pattern') is not None:
        scalar, scalar_cond, blocks = _compile_pattern(
            cps, validate['pattern'])
        return RuleProgram(
            policy_name=policy.name, rule_name=name,
            policy_index=p_idx, rule_index=r_idx,
            scalar=scalar, scalar_condition=scalar_cond,
            elements=tuple(blocks),
            pass_message=f"validation rule '{name}' passed.",
            background=policy.background, rule_raw=rule)
    if validate.get('anyPattern') is not None:
        raise CompileError('anyPattern compiled per-sub-pattern in v2')
    raise CompileError('no pattern')


def _require_simple_match(match: dict) -> None:
    """The device path precomputes match host-side; that host precompute
    supports everything, so only sanity-check shape here."""
    if not isinstance(match, dict):
        raise CompileError('bad match block')


def _check_no_vars(value: Any) -> None:
    if isinstance(value, str) and (is_variable(value) or is_reference(value)):
        raise CompileError(f'variable in pattern: {value!r}')
    if isinstance(value, dict):
        for k, v in value.items():
            _check_no_vars(k)
            _check_no_vars(v)
    if isinstance(value, list):
        for v in value:
            _check_no_vars(v)


def _compile_pattern(cps: CompiledPolicySet, pattern: Any):
    """Compile a pattern tree rooted at the resource."""
    _check_no_vars(pattern)
    if not isinstance(pattern, dict):
        raise CompileError('top-level pattern must be a map')
    scalar_parts: List[BoolExpr] = []
    cond_parts: List[BoolExpr] = []
    blocks: List[ElementBlock] = []
    _walk_map(cps, pattern, (), scalar_parts, cond_parts, blocks)
    scalar = BoolExpr.all(scalar_parts) if scalar_parts else None
    cond = BoolExpr.all(cond_parts) if cond_parts else None
    return scalar, cond, blocks


def _walk_map(cps: CompiledPolicySet, pattern: dict, path: Tuple[str, ...],
              scalar_parts: List[BoolExpr], cond_parts: List[BoolExpr],
              blocks: List[ElementBlock]) -> None:
    for key, value in pattern.items():
        a = anchor_mod.parse(key)
        bare = a.key if a else key
        child_path = path + (bare,)
        if a is not None and anchor_mod.is_global(a):
            raise CompileError('global anchors not vectorized')
        if a is not None and anchor_mod.is_condition(a):
            # map-level conditional anchor: mismatch or missing → rule skip
            if isinstance(value, (dict, list)):
                raise CompileError('nested conditional anchors not vectorized')
            cond_parts.append(_compile_leaf(cps, child_path, value,
                                            missing_ok=False))
            continue
        if a is not None and anchor_mod.is_negation(a):
            slot = Slot(child_path)
            cps.slot_id(slot)
            scalar_parts.append(BoolExpr.of(Leaf(slot, 'absent')))
            continue
        if a is not None and anchor_mod.is_existence(a):
            if not isinstance(value, list) or not value or \
                    not all(isinstance(e, dict) for e in value):
                raise CompileError('existence anchor pattern must be a '
                                   'list of maps')
            for elem_pattern in value:
                blocks.append(_compile_element_block(
                    cps, child_path, elem_pattern, mode='exists'))
            continue
        missing_ok = a is not None and anchor_mod.is_equality(a)
        if isinstance(value, dict):
            if missing_ok:
                raise CompileError('=() on maps not vectorized')
            if _has_wildcard_key(value):
                raise CompileError('wildcard keys not vectorized')
            _walk_map(cps, value, child_path, scalar_parts, cond_parts,
                      blocks)
        elif isinstance(value, list):
            if not value:
                raise CompileError('empty pattern array')
            first = value[0]
            if isinstance(first, dict):
                if len(value) != 1:
                    raise CompileError('multi-element array patterns not '
                                       'vectorized')
                blocks.append(_compile_element_block(cps, child_path, first,
                                                     mode='forall',
                                                     missing_ok=missing_ok))
            elif isinstance(first, (str, int, float, bool)) or first is None:
                # every array element must match the scalar pattern
                slot_path = child_path + ('*',)
                constraint = _compile_leaf(cps, slot_path, first,
                                           missing_ok=False)
                blocks.append(ElementBlock(
                    array_path=child_path, condition=None,
                    constraint=constraint))
            else:
                raise CompileError('unsupported array pattern')
        else:
            scalar_parts.append(_compile_leaf(cps, child_path, value,
                                              missing_ok=missing_ok))


def _has_wildcard_key(pattern: dict) -> bool:
    return any(('*' in k or '?' in k) for k in pattern)


def _compile_element_block(cps: CompiledPolicySet, array_path: Tuple[str, ...],
                           elem_pattern: dict, mode: str,
                           missing_ok: bool = False) -> ElementBlock:
    if missing_ok:
        raise CompileError('=() array anchors not vectorized')
    cond_parts: List[BoolExpr] = []
    cons_parts: List[BoolExpr] = []
    for key, value in elem_pattern.items():
        a = anchor_mod.parse(key)
        bare = a.key if a else key
        slot_path = array_path + ('*', bare)
        if a is not None and anchor_mod.is_condition(a):
            if isinstance(value, (dict, list)):
                raise CompileError('nested element conditions not vectorized')
            cond_parts.append(_compile_leaf(cps, slot_path, value,
                                            missing_ok=False))
            continue
        if a is not None and anchor_mod.is_negation(a):
            slot = Slot(slot_path)
            cps.slot_id(slot)
            cons_parts.append(BoolExpr.of(Leaf(slot, 'absent')))
            continue
        if a is not None and not anchor_mod.is_equality(a):
            raise CompileError(f'anchor {key} not vectorized in elements')
        missing_ok_leaf = a is not None and anchor_mod.is_equality(a)
        if isinstance(value, dict):
            # nested map inside element: flatten one extra level of scalars
            _flatten_nested(cps, slot_path, value, cons_parts,
                            missing_ok_leaf)
        elif isinstance(value, list):
            raise CompileError('nested arrays not vectorized')
        else:
            cons_parts.append(_compile_leaf(cps, slot_path, value,
                                            missing_ok=missing_ok_leaf))
    if not cons_parts and not cond_parts:
        raise CompileError('empty element pattern')
    condition = BoolExpr.all(cond_parts) if cond_parts else None
    if cons_parts:
        constraint = BoolExpr.all(cons_parts)
    else:
        true_slot = Slot(array_path + ('*',))
        cps.slot_id(true_slot)
        constraint = BoolExpr.of(Leaf(true_slot, 'true'))
    if mode == 'exists':
        return ElementBlock(array_path=array_path, condition=None,
                            constraint=BoolExpr.all(cond_parts + cons_parts),
                            mode='exists')
    return ElementBlock(array_path=array_path, condition=condition,
                        constraint=constraint)


def _flatten_nested(cps: CompiledPolicySet, base_path: Tuple[str, ...],
                    pattern: dict, out: List[BoolExpr],
                    missing_ok: bool) -> None:
    """Flatten nested scalar maps under an element, e.g.
    containers[].securityContext.privileged."""
    for key, value in pattern.items():
        a = anchor_mod.parse(key)
        bare = a.key if a else key
        if a is not None and anchor_mod.is_negation(a):
            slot = Slot(base_path + (bare,))
            cps.slot_id(slot)
            out.append(BoolExpr.of(Leaf(slot, 'absent')))
            continue
        if a is not None and not anchor_mod.is_equality(a):
            raise CompileError('nested anchors not vectorized')
        leaf_missing_ok = missing_ok or (
            a is not None and anchor_mod.is_equality(a))
        if isinstance(value, dict):
            _flatten_nested(cps, base_path + (bare,), value, out,
                            leaf_missing_ok)
        elif isinstance(value, list):
            raise CompileError('nested arrays not vectorized')
        else:
            out.append(_compile_leaf(cps, base_path + (bare,), value,
                                     missing_ok=leaf_missing_ok))


# ---------------------------------------------------------------------------
# Leaf compilation

def _compile_leaf(cps: CompiledPolicySet, path: Tuple[str, ...], pattern: Any,
                  missing_ok: bool) -> BoolExpr:
    slot = Slot(path)
    if slot.elem and path.count('*') > 1:
        raise CompileError('nested element dimensions not vectorized')
    cps.slot_id(slot)

    def L(op, operand=None):
        return BoolExpr.of(Leaf(slot, op, operand, missing_ok))

    if isinstance(pattern, bool):
        return L('eq_bool', pattern)
    if pattern is None:
        return L('eq_null')
    if isinstance(pattern, int):
        return L('eq_int', pattern)
    if isinstance(pattern, float):
        milli = Fraction(str(pattern)) * 1000
        if milli.denominator != 1:
            raise CompileError('sub-milli float pattern not exact on device')
        return L('eq_float', pattern)
    if isinstance(pattern, dict):
        raise CompileError('map leaf')
    if isinstance(pattern, str):
        return _compile_string_pattern(slot, pattern, missing_ok)
    raise CompileError(f'unsupported leaf type {type(pattern).__name__}')


def _compile_string_pattern(slot: Slot, pattern: str,
                            missing_ok: bool) -> BoolExpr:
    """Compile the string operator grammar
    (reference: pkg/engine/pattern/pattern.go:152 validateStringPatterns)."""
    if pattern == '*':
        return BoolExpr.of(Leaf(slot, 'star', None, missing_ok))
    ors = []
    # exact equality short-circuit (value == pattern) is subsumed by terms
    for condition in pattern.split('|'):
        ands = []
        for term in condition.strip(' ').split('&'):
            ands.append(_compile_string_term(slot, term.strip(' '),
                                             missing_ok))
        ors.append(BoolExpr.all(ands))
    return BoolExpr.any(ors)


def _compile_string_term(slot: Slot, term: str, missing_ok: bool) -> BoolExpr:
    op = leaf_pattern.get_operator_from_string_pattern(term)
    if op == leaf_pattern.OP_IN_RANGE:
        m = leaf_pattern.IN_RANGE_RE.match(term)
        return BoolExpr.all([
            _compile_string_term(slot, f'>= {m.group(1)}', missing_ok),
            _compile_string_term(slot, f'<= {m.group(2)}', missing_ok)])
    if op == leaf_pattern.OP_NOT_IN_RANGE:
        m = leaf_pattern.NOT_IN_RANGE_RE.match(term)
        return BoolExpr.any([
            _compile_string_term(slot, f'< {m.group(1)}', missing_ok),
            _compile_string_term(slot, f'> {m.group(2)}', missing_ok)])
    operand = term[len(op):].strip(' ')
    cmp = _CMP_OF_OP[op]

    def L(lop, loperand=None):
        return BoolExpr.of(Leaf(slot, lop, loperand, missing_ok))

    alternatives: List[BoolExpr] = []
    # 1. duration comparison (only if operand parses as Go duration)
    try:
        nanos = parse_duration(operand)
        alternatives.append(L('cmp_dur', (cmp, nanos)))
    except (ValueError, TypeError):
        pass
    # 2. quantity comparison (only if operand parses as k8s quantity)
    try:
        q = Quantity.parse(operand)
        milli = q.value * 1000
        if milli.denominator != 1:
            raise CompileError('sub-milli quantity operand')
        alternatives.append(L('cmp_qty', (cmp, int(milli))))
    except ValueError:
        pass
    # 3. wildcard string comparison (only for == / !=)
    if cmp in (CMP_EQ, CMP_NE):
        str_check = _compile_wildcard_eq(slot, operand, missing_ok)
        if cmp == CMP_NE:
            str_check = BoolExpr.negate(str_check)
            # NotEqual with missing key still fails the walk: negation of a
            # missing-fails leaf would wrongly pass — force explicit handling
            str_check = BoolExpr.all([
                BoolExpr.of(Leaf(slot, 'convertible', None, missing_ok)),
                str_check])
        alternatives.append(str_check)
    if not alternatives:
        raise CompileError(f'no vectorizable interpretation for {term!r}')
    return BoolExpr.any(alternatives)


def _compile_wildcard_eq(slot: Slot, operand: str,
                         missing_ok: bool) -> BoolExpr:
    """Classify a wildcard pattern into a vectorizable string class."""
    def L(op, loperand=None):
        return BoolExpr.of(Leaf(slot, op, loperand, missing_ok))

    if len(operand.encode()) > STR_LEN:
        raise CompileError('operand longer than encoded string window')
    has_star = '*' in operand
    has_q = '?' in operand
    if not has_star and not has_q:
        return L('eq_str', operand)
    if operand == '*':
        return L('any_str')
    if operand == '?*':
        return L('nonempty')
    if has_q:
        raise CompileError(f'general ? wildcard not vectorized: {operand!r}')
    parts = operand.split('*')
    if len(parts) == 2 and parts[0] and not parts[1]:
        return L('prefix', parts[0])
    if len(parts) == 2 and not parts[0] and parts[1]:
        if len(parts[1].encode()) > TAIL_LEN:
            raise CompileError('suffix longer than tail window')
        return L('suffix', parts[1])
    if len(parts) == 3 and parts[0] and parts[2] and not parts[1]:
        # "a*b": prefix a AND suffix b AND len >= len(a)+len(b)
        if len(parts[2].encode()) > TAIL_LEN:
            raise CompileError('suffix longer than tail window')
        return BoolExpr.all([
            L('prefix', parts[0]), L('suffix', parts[2]),
            L('min_len', len(parts[0].encode()) + len(parts[2].encode()))])
    raise CompileError(f'wildcard class not vectorized: {operand!r}')
