"""Policy-set compiler v2: validate rules → tri-state status programs.

Compiles pattern / anyPattern / deny / preconditions rules into
:class:`StatusExpr` trees that mirror the reference's anchor walk
(reference: pkg/engine/validate/validate.go, pkg/engine/anchor/handlers.go)
and condition evaluation (reference: pkg/engine/variables/operator/*.go).
Rules outside the vocabulary — context entries, foreach, manifests,
unresolvable variables, exotic operand shapes — fall back to the host
engine, preserving exact semantics.  Individual undecidable *checks*
(long strings, overflowing arrays, runtime wildcards) surface as
STATUS_HOST per resource instead of forcing the whole rule to host.
"""

from __future__ import annotations

import json
import re
from fractions import Fraction
from typing import Any, List, Optional, Tuple

from ..api.policy import Policy
from ..autogen.autogen import compute_rules
from ..engine import anchor as anchor_mod
from ..engine import pattern as leaf_pattern
from ..engine.validate_pattern import has_nested_anchors
from ..engine.variables import is_reference, is_variable
from ..observability.coverage import (REASON_HOST_CLOSURE,
                                      PLACEMENT_DEVICE, PLACEMENT_HOST,
                                      RulePlacement)
from ..utils.duration import parse_duration
from ..utils.quantity import Quantity
from .ir import (CMP_EQ, CMP_GE, CMP_GT, CMP_LE, CMP_LT, CMP_NE, STR_LEN,
                 TAIL_LEN, BoolExpr, CompileError, CompiledPolicySet,
                 CondCheck, GatherSlot, Leaf, RuleProgram, Slot, StatusExpr)

_CMP_OF_OP = {
    leaf_pattern.OP_MORE: CMP_GT,
    leaf_pattern.OP_MORE_EQUAL: CMP_GE,
    leaf_pattern.OP_LESS: CMP_LT,
    leaf_pattern.OP_LESS_EQUAL: CMP_LE,
    leaf_pattern.OP_EQUAL: CMP_EQ,
    leaf_pattern.OP_NOT_EQUAL: CMP_NE,
}

# a condition key of exactly one {{ ... }} expression
_SINGLE_VAR_RE = re.compile(r'^\{\{(.*)\}\}$', re.DOTALL)


def compile_policies(policies: List[Policy]) -> CompiledPolicySet:
    cps = CompiledPolicySet()
    cps.policies = policies
    for p_idx, policy in enumerate(policies):
        for r_idx, rule in enumerate(compute_rules(policy)):
            name = rule.get('name', '')
            validate = rule.get('validate')
            path = 'pss' if isinstance(validate, dict) and \
                validate.get('podSecurity') is not None else 'validate'
            if not validate:
                # mutate/generate-only rules produce no validate responses
                # in a background scan (engine.py:254-260 _process_rule);
                # verifyImages validation stays host-side (network-bound)
                if any(iv.get('verifyDigest', True) or
                       iv.get('required', True)
                       for iv in rule.get('verifyImages') or []):
                    cps.host_rules.append((p_idx, rule, policy))
                    cps.placements.append(RulePlacement(
                        policy.name, name, path, PLACEMENT_HOST,
                        REASON_HOST_CLOSURE,
                        'verifyImages rules are network-bound', p_idx))
                continue
            try:
                program = _compile_rule(cps, policy, p_idx, r_idx, rule)
            except CompileError as e:
                cps.host_rules.append((p_idx, rule, policy))
                cps.placements.append(RulePlacement(
                    policy.name, name, path, PLACEMENT_HOST, e.reason,
                    str(e), p_idx))
                continue
            cps.programs.append(program)
            cps.placements.append(RulePlacement(
                policy.name, name, path, PLACEMENT_DEVICE, None, '',
                p_idx))
    return cps


def _compile_rule(cps: CompiledPolicySet, policy: Policy, p_idx: int,
                  r_idx: int, rule: dict) -> RuleProgram:
    if not rule.get('validate'):
        raise CompileError('not a validate rule')
    validate = rule['validate']
    context_spec = None
    context_inputs = None
    if rule.get('context'):
        # compilable when every entry is a cluster-data lookup whose
        # value feeds NO compiled lane — the load's success/failure
        # semantics are enforced per resource by the scanner (imageData
        # entries stay host-side: network-bound)
        entries = rule['context']
        if not isinstance(entries, list):
            raise CompileError('malformed context block')
        for entry in entries:
            e = entry or {}
            if not (e.get('configMap') or e.get('apiCall') or
                    e.get('variable')):
                raise CompileError(
                    'imageRegistry context entries require the host '
                    'engine', reason='api_call')
        body = json.dumps({'v': validate,
                           'p': rule.get('preconditions')})
        for entry in entries:
            nm = str((entry or {}).get('name', ''))
            if nm and re.search(r'\b' + re.escape(nm) + r'\b', body):
                raise CompileError(
                    'context entry value feeds compiled lanes')
        context_spec = tuple(entries)
        # cacheable when every consumed variable is request.object-rooted
        # AND no entry evaluates bare (un-braced) expressions per
        # resource — 'variable' entries run a jmesPath against the full
        # context, so their outcome can depend on more than the captured
        # inputs (the load then re-runs per resource)
        from ..engine.variables import RE_VARIABLES as _RV
        exprs = []
        cacheable = all((e or {}).get('configMap') or (e or {}).get('apiCall')
                        for e in entries)
        if cacheable:
            for m in _RV.finditer(json.dumps(entries)):
                expr = m.group(2)[2:-2].strip()
                if not expr.startswith('request.object'):
                    cacheable = False
                    break
                exprs.append(expr)
        context_inputs = tuple(sorted(set(exprs))) if cacheable else None
    if validate.get('manifests') is not None:
        raise CompileError('manifests rules require the host engine',
                           reason='host_closure')
    if not isinstance(rule.get('match', {}) or {}, dict) or \
            not isinstance(rule.get('exclude', {}) or {}, dict):
        raise CompileError('bad match/exclude block')

    name = rule.get('name', '')
    units: List[StatusExpr] = []
    pass_messages = (f"validation rule '{name}' passed.",)
    error_messages: List[str] = []
    pss = None
    skip_message = None
    fail_sites: Optional[List[str]] = None
    fail_prefix = None
    deny_fail_message = None
    any_fail_sites = None
    any_fail_prefix = None
    msg = (validate.get('message') or '') if isinstance(validate, dict) else ''
    static_msg = isinstance(msg, str) and '{{' not in msg and '$(' not in msg

    # preconditions gate everything (engine.py Validator.validate order)
    if rule.get('preconditions') is not None:
        pre = _compile_conditions(cps, rule['preconditions'])
        plan = _error_plan(cps, rule['preconditions'],
                           'failed to evaluate preconditions', error_messages)
        units.append(StatusExpr('precond', expr=pre, operand=plan))

    if validate.get('deny') is not None:
        conditions = (validate['deny'] or {}).get('conditions')
        deny = _compile_conditions(cps, conditions)
        plan = _error_plan(
            cps, conditions,
            'failed to substitute variables in deny conditions',
            error_messages)
        units.append(StatusExpr('deny', expr=deny, operand=plan))
        if static_msg:
            # deny FAIL message is the (static) message verbatim, or the
            # no-message fallback (engine.py:446 _deny_message)
            deny_fail_message = msg or \
                f'validation error: rule {name} failed'
    elif validate.get('pattern') is not None:
        if static_msg:
            # FAIL messages with a non-empty path are fully determined by
            # (static message, rule name, failing path) — engine.py:543
            # _error_message / reference validation.go:722
            fail_sites = []
            if msg:
                dot = msg if msg.endswith('.') else msg + '.'
                fail_prefix = (f'validation error: {dot} rule {name} '
                               f'failed at path ')
            else:
                fail_prefix = (f'validation error: rule {name} '
                               f'failed at path ')
        units.append(_compile_pattern_status(cps, validate['pattern'],
                                             sites=fail_sites))
    elif validate.get('anyPattern') is not None:
        pats = validate['anyPattern']
        if not isinstance(pats, list):
            raise CompileError('anyPattern must be a list')
        any_sites: Optional[List[List[str]]] = \
            [[] for _ in pats] if static_msg else None
        children = [
            _compile_pattern_status(
                cps, p, in_any_pattern=True,
                sites=any_sites[i] if any_sites is not None else None)
            for i, p in enumerate(pats)]
        units.append(StatusExpr('any', children=tuple(children)))
        # pass message carries the index of the sub-pattern that matched
        # (engine.py:514, reference: pkg/engine/validation.go:640)
        pass_messages = tuple(
            f"validation rule '{name}' anyPattern[{i}] passed."
            for i in range(len(pats)))
        if any_sites is not None:
            any_fail_sites = tuple(tuple(s) for s in any_sites)
            # buildAnyPatternErrorMessage prefix (engine.py:565)
            if not msg:
                any_fail_prefix = 'validation error: '
            elif msg.endswith('.'):
                any_fail_prefix = f'validation error: {msg} '
            else:
                any_fail_prefix = f'validation error: {msg}. '
    elif validate.get('podSecurity') is not None:
        # host dispatch order: podSecurity before foreach (engine.py:403)
        from .pss_compile import compile_pod_security
        units.append(compile_pod_security(cps, validate['podSecurity'],
                                          rule))
        # PSS pass messages are capitalized (engine.py:605)
        pass_messages = (f"Validation rule '{name}' passed.",)
        ps = validate['podSecurity']
        pss = (ps.get('level', ''), ps.get('version', ''))
    elif validate.get('foreach') is not None:
        units.append(_compile_foreach(cps, validate['foreach']))
        # foreach pass/skip messages are static (engine.py:625-630)
        pass_messages = ('rule passed',)
        skip_message = 'rule skipped'
        if static_msg:
            # a deny-decided element failure wraps the (static) deny
            # message (engine.py:665 'validation failure: …'); the
            # evaluator emits fdet>=0 only for unambiguous deny fails
            inner = msg or f'validation error: rule {name} failed'
            deny_fail_message = f'validation failure: {inner}'
    else:
        raise CompileError('no compilable validate sub-key')

    return RuleProgram(
        policy_name=policy.name, rule_name=name,
        policy_index=p_idx, rule_index=r_idx,
        status=StatusExpr.seq(units),
        pass_messages=pass_messages,
        error_messages=tuple(error_messages), pss=pss,
        skip_message=skip_message,
        background=policy.background, rule_raw=rule,
        context_spec=context_spec, context_inputs=context_inputs,
        fail_sites=tuple(fail_sites) if fail_sites is not None else None,
        fail_prefix=fail_prefix, deny_fail_message=deny_fail_message,
        any_fail_sites=any_fail_sites, any_fail_prefix=any_fail_prefix)


def _error_plan(cps: CompiledPolicySet, conditions: Any, prefix: str,
                messages: List[str]) -> Tuple[Tuple[GatherSlot, int], ...]:
    """Ordered (gather, message-index) plan for unresolvable condition
    variables.  Mirrors the substitution traversal order
    (variables.py _traverse, reference: pkg/engine/jsonutils/traverse.go)
    so the first missing variable produces the host's exact
    substitution-error message (engine.py:388,431)."""
    leaves: List[Tuple[str, str]] = []

    def walk(node: Any, path: str) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f'{path}/{k}')
        elif isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f'{path}/{i}')
        elif isinstance(node, str):
            m = _SINGLE_VAR_RE.match(node.strip())
            if m:
                leaves.append((m.group(1).strip(), path))

    walk(conditions, '')
    plan: List[Tuple[GatherSlot, int]] = []
    for var, path in leaves:
        gather = GatherSlot(var)
        if gather not in cps.gather_index:
            raise CompileError(f'unplanned variable {var!r} in conditions')
        messages.append(
            f'{prefix}: failed to resolve {var} at path {path}: '
            f'Unknown key "{var}" in path')
        plan.append((gather, len(messages) - 1))
    return tuple(plan)


# ---------------------------------------------------------------------------
# Pattern compilation (tree-walk → StatusExpr)

def _check_no_vars(value: Any) -> None:
    if isinstance(value, str) and (is_variable(value) or is_reference(value)):
        raise CompileError(f'variable in pattern: {value!r}')
    if isinstance(value, dict):
        for k, v in value.items():
            _check_no_vars(k)
            _check_no_vars(v)
    if isinstance(value, list):
        for v in value:
            _check_no_vars(v)


# wildcard pattern-key path segment: '\x00wk:<pattern>' resolves, per
# resource, to the FIRST map key matching <pattern> (the device form of
# wildcards.ExpandInMetadata — reference pkg/engine/wildcards/wildcards.go:62)
WILD_KEY_MARK = '\x00wk:'
# site template sentinel: the failing path embeds a per-resource resolved
# key, so the message cannot be synthesized — FAIL cells go to the host
DYNAMIC_SITE = '\x00dyn'


def _wild_key_allowed(path: Tuple[str, ...]) -> bool:
    """Wildcard pattern keys resolve per-resource only under
    metadata.labels / metadata.annotations — the exact scope of the
    reference's ExpandInMetadata (wildcards.go:62, applied at every
    validateMap level, so any autogen prefix is fine)."""
    return len(path) >= 2 and path[-1] in ('labels', 'annotations') \
        and path[-2] == 'metadata'


def _path_template(path: Tuple[str, ...], parent: bool = False) -> str:
    """Host walk path for a slot path: '/spec/containers/{e0}/image/'.
    ``parent`` drops the last component (the map-level '*' shortcut
    reports the parent map's path — anchor.py:214)."""
    parts = path[:-1] if parent else path
    if any(p.startswith(WILD_KEY_MARK) for p in parts):
        return DYNAMIC_SITE
    out = '/'
    e = 0
    for p in parts:
        if p == '*':
            out += '{e%d}/' % e
            e += 1
        else:
            out += f'{p}/'
    return out


def _new_site(sites: Optional[List[str]], path: Tuple[str, ...],
              parent: bool = False) -> Optional[int]:
    if sites is None:
        return None
    sites.append(_path_template(path, parent))
    return len(sites) - 1


def _compile_pattern_status(cps: CompiledPolicySet, pattern: Any,
                            in_any_pattern: bool = False,
                            sites: Optional[List[str]] = None) -> StatusExpr:
    """Compile one pattern tree rooted at the resource document."""
    _check_no_vars(pattern)
    if not isinstance(pattern, dict):
        raise CompileError('top-level pattern must be a map')
    tracked: List[Slot] = []
    status = _compile_map(cps, pattern, (), tracked, sites)
    if in_any_pattern:
        # anyPattern sub-failures stay failures regardless of missing anchor
        # keys (engine.py:524 treats empty-path errors as plain failures) —
        # but an empty-path failure has a different message ('failed: {pe}'
        # vs 'failed at path {p}'), so the fail-detail is guarded on all
        # tracked anchor keys being present
        if sites is not None and tracked:
            guards = [BoolExpr.of(Leaf(s, 'star')) for s in tracked]
            return StatusExpr('failguard', expr=BoolExpr.all(guards),
                              sub=status)
        return status
    if not tracked:
        return status
    # single-pattern quirk (validate_pattern.match_pattern:38 +
    # engine.py:493): a plain FAIL while any tracked condition/existence/
    # negation anchor key was missing (null counts as missing) surfaces as
    # ERROR with empty path → undecidable on device, send to host
    guards = [BoolExpr.of(Leaf(s, 'star')) for s in tracked]
    return StatusExpr('trackfail', expr=BoolExpr.all(guards), sub=status)


def _phase1_sort_key(key: str) -> str:
    return key


def _compile_map(cps: CompiledPolicySet, pattern: dict,
                 path: Tuple[str, ...], tracked: List[Slot],
                 sites: Optional[List[str]] = None) -> StatusExpr:
    """Compile a pattern map at ``path`` (``'*'`` marks element scope).

    Mirrors _validate_map: phase 1 anchors in sorted key order, then plain
    keys with nested-anchor/global keys first (validate_pattern.py:77-92).
    The caller has already guarded that the resource node is a map.
    """
    anchors, plains = {}, {}
    for key, value in pattern.items():
        a = anchor_mod.parse(key)
        if anchor_mod.is_condition(a) or anchor_mod.is_existence(a) or \
                anchor_mod.is_equality(a) or anchor_mod.is_negation(a):
            anchors[key] = (a, value)
        else:
            plains[key] = (a, value)

    children: List[StatusExpr] = []

    for key in sorted(anchors, key=_phase1_sort_key):
        a, value = anchors[key]
        if _key_has_wildcard(a.key):
            # first-match key resolution happens at encode time (the
            # encoder sees the document); the host sorts phase-1 anchors
            # by the RESOLVED key, so sibling ordering is only exact
            # when the wildcard key is alone in its map
            if not _wild_key_allowed(path) or anchor_mod.is_existence(a) \
                    or len(pattern) != 1:
                raise CompileError(
                    f'wildcard pattern key not vectorized: {key}')
            if not isinstance(value, (str, int, float, bool)) \
                    and value is not None:
                raise CompileError(
                    f'wildcard pattern key with non-scalar value: {key}')
            # ExpandInMetadata stringifies the pattern values it rewrites
            value = str(value)
            child_path = path + (WILD_KEY_MARK + a.key,)
        else:
            child_path = path + (a.key,)
        slot = Slot(child_path)
        _require_depth(slot)
        cps.slot_id(slot)
        if anchor_mod.is_condition(a):
            tracked.append(slot)
            sub = _compile_element(cps, value, child_path, tracked, sites)
            children.append(StatusExpr('cond', slot=slot, sub=sub))
        elif anchor_mod.is_equality(a):
            sub = _compile_element(cps, value, child_path, tracked, sites)
            children.append(StatusExpr('equality', slot=slot, sub=sub))
        elif anchor_mod.is_negation(a):
            tracked.append(slot)
            children.append(StatusExpr(
                'negation', slot=slot,
                fail_site=_new_site(sites, child_path)))
        elif anchor_mod.is_existence(a):
            tracked.append(slot)
            if not isinstance(value, list) or not value or \
                    not all(isinstance(e, dict) for e in value):
                raise CompileError('existence anchor pattern must be a '
                                   'list of maps')
            for elem_pattern in value:
                # existence failures always report the anchored key's
                # path (anchor.py:250), so element subtrees need no sites
                elem_sub = _compile_elem_map(cps, elem_pattern,
                                             child_path + ('*',), tracked,
                                             None)
                children.append(StatusExpr(
                    'exists', slot=slot, sub=elem_sub,
                    fail_site=_new_site(sites, child_path)))

    for key in _plain_order(plains):
        a, value = plains[key]
        bare = a.key if a else key
        if _key_has_wildcard(bare):
            if not _wild_key_allowed(path) or a is not None \
                    or len(pattern) != 1:
                raise CompileError(
                    f'wildcard pattern key not vectorized: {key}')
            if not isinstance(value, (str, int, float, bool)) \
                    and value is not None:
                raise CompileError(
                    f'wildcard pattern key with non-scalar value: {key}')
            if value != '*':
                value = str(value)
            child_path = path + (WILD_KEY_MARK + bare,)
        else:
            child_path = path + (bare,)
        if a is not None and anchor_mod.is_global(a):
            slot = Slot(child_path)
            _require_depth(slot)
            cps.slot_id(slot)
            sub = _compile_element(cps, value, child_path, tracked, sites)
            children.append(StatusExpr('global', slot=slot, sub=sub))
            continue
        if a is not None and anchor_mod.is_add_if_not_present(a):
            continue  # mutation-only anchor: no-op during validation
        # default key (anchor.py handle_element default branch): the
        # "*" pattern passes on any non-null value, fails when missing —
        # reported at the parent map's path (anchor.py:214)
        if value == '*':
            slot = Slot(child_path)
            _require_depth(slot)
            cps.slot_id(slot)
            children.append(StatusExpr(
                'leaf', expr=BoolExpr.of(Leaf(slot, 'star')),
                fail_site=_new_site(sites, child_path, parent=True)))
            continue
        children.append(_compile_element(cps, value, child_path, tracked,
                                         sites))

    return StatusExpr.seq(children)


def _plain_order(plains: dict) -> List[str]:
    """validate_pattern._sorted_nested_anchor_keys ordering."""
    front, back = [], []
    for k in sorted(plains):
        a, v = plains[k]
        if anchor_mod.is_global(a) or has_nested_anchors(v):
            front.insert(0, k)
        else:
            back.append(k)
    return front + back


def _require_depth(slot: Slot) -> None:
    if slot.depth > 2:
        raise CompileError('more than two element dimensions not vectorized')


def _compile_element(cps: CompiledPolicySet, pattern: Any,
                     path: Tuple[str, ...], tracked: List[Slot],
                     sites: Optional[List[str]] = None) -> StatusExpr:
    """Compile _validate_element dispatch for the value at ``path``.

    Mirrors validate_pattern._validate_element: maps need a map resource,
    lists need a list resource, scalars compare leaf-wise (arrays of
    scalars must all match — handled in eval via the array-addendum).
    """
    slot = Slot(path)
    _require_depth(slot)
    cps.slot_id(slot)
    if isinstance(pattern, dict):
        is_map = StatusExpr('leaf', expr=BoolExpr.of(Leaf(slot, 'is_map')),
                            fail_site=_new_site(sites, path))
        sub = _compile_map(cps, pattern, path, tracked, sites)
        return StatusExpr.seq([is_map, sub])
    if isinstance(pattern, list):
        if not pattern:
            raise CompileError('empty pattern array')
        first = pattern[0]
        is_arr = StatusExpr('leaf', expr=BoolExpr.of(Leaf(slot, 'is_array')),
                            fail_site=_new_site(sites, path))
        if isinstance(first, dict):
            # validateArrayOfMaps uses only the first pattern element
            # (reference: pkg/engine/validate/validate.go:168-173)
            elem_sub = _compile_elem_map(cps, first, path + ('*',), tracked,
                                         sites)
            forall = StatusExpr('forall', slot=slot, sub=elem_sub,
                                fail_site=_new_site(sites, path))
            return StatusExpr.seq([is_arr, forall])
        if isinstance(first, (str, int, float, bool)) or first is None:
            # scalar array pattern: every element must match the scalar
            # (validate.go:104 routes the array through the scalar leaf,
            # validate_pattern.py:61-66 checks each element); failures
            # report the ARRAY's path, no element index
            check = _compile_leaf(cps, path + ('*',), first)
            return StatusExpr.seq(
                [is_arr, StatusExpr('scalars', slot=slot, expr=check,
                                    fail_site=_new_site(sites, path))])
        raise CompileError('typed array patterns not vectorized')
    if isinstance(pattern, (str, int, float, bool)) or pattern is None:
        return StatusExpr('leaf', expr=_compile_leaf(cps, path, pattern),
                          fail_site=_new_site(sites, path))
    raise CompileError(f'unsupported pattern type {type(pattern).__name__}')


def _compile_elem_map(cps: CompiledPolicySet, elem_pattern: dict,
                      elem_path: Tuple[str, ...], tracked: List[Slot],
                      sites: Optional[List[str]] = None) -> StatusExpr:
    """Compile the per-element pattern of an array-of-maps walk.

    validateArrayOfMaps calls validateResourceElement per element, so a
    non-map element is a plain FAIL (is_map guard at element scope).
    """
    if not isinstance(elem_pattern, dict):
        raise CompileError('element pattern must be a map')
    slot = Slot(elem_path)
    _require_depth(slot)
    cps.slot_id(slot)
    is_map = StatusExpr('leaf', expr=BoolExpr.of(Leaf(slot, 'is_map')),
                        fail_site=_new_site(sites, elem_path))
    sub = _compile_map(cps, elem_pattern, elem_path, tracked, sites)
    return StatusExpr.seq([is_map, sub])


def _key_has_wildcard(key: str) -> bool:
    return '*' in key or '?' in key


# ---------------------------------------------------------------------------
# Leaf compilation

def _compile_leaf(cps: CompiledPolicySet, path: Tuple[str, ...],
                  pattern: Any) -> BoolExpr:
    slot = Slot(path)
    _require_depth(slot)
    cps.slot_id(slot)

    def L(op, operand=None):
        return BoolExpr.of(Leaf(slot, op, operand))

    if isinstance(pattern, bool):
        return L('eq_bool', pattern)
    if pattern is None:
        return L('eq_null')
    if isinstance(pattern, int):
        if abs(pattern) * 1000 > (1 << 63) - 1:
            raise CompileError('integer pattern exceeds the milli lane')
        return L('eq_int', pattern)
    if isinstance(pattern, float):
        milli = Fraction(str(pattern)) * 1000
        if milli.denominator != 1:
            raise CompileError('sub-milli float pattern not exact on device')
        return L('eq_float', pattern)
    if isinstance(pattern, str):
        return _compile_string_pattern(slot, pattern)
    raise CompileError(f'unsupported leaf type {type(pattern).__name__}')


def _compile_string_pattern(slot: Slot, pattern: str) -> BoolExpr:
    """Compile the string operator grammar
    (reference: pkg/engine/pattern/pattern.go:152 validateStringPatterns)."""
    # the host short-circuits when the value equals the whole pattern
    # string literally (pattern.py:133) — e.g. value '>5' vs pattern '>5'
    ors = []
    if len(pattern.encode('utf-8')) <= STR_LEN:
        ors.append(BoolExpr.of(Leaf(slot, 'eq_str', pattern)))
    for condition in pattern.split('|'):
        ands = []
        for term in condition.strip(' ').split('&'):
            ands.append(_compile_string_term(slot, term.strip(' ')))
        ors.append(BoolExpr.all(ands))
    return BoolExpr.any(ors)


def _compile_string_term(slot: Slot, term: str) -> BoolExpr:
    op = leaf_pattern.get_operator_from_string_pattern(term)
    if op == leaf_pattern.OP_IN_RANGE:
        m = leaf_pattern.IN_RANGE_RE.match(term)
        return BoolExpr.all([
            _compile_string_term(slot, f'>= {m.group(1)}'),
            _compile_string_term(slot, f'<= {m.group(2)}')])
    if op == leaf_pattern.OP_NOT_IN_RANGE:
        m = leaf_pattern.NOT_IN_RANGE_RE.match(term)
        return BoolExpr.any([
            _compile_string_term(slot, f'< {m.group(1)}'),
            _compile_string_term(slot, f'> {m.group(2)}')])
    operand = term[len(op):].strip(' ') if op else term
    cmp = _CMP_OF_OP[op] if op else CMP_EQ
    if not op:
        operand = term

    def L(lop, loperand=None):
        return BoolExpr.of(Leaf(slot, lop, loperand))

    alternatives: List[BoolExpr] = []
    # 1. duration comparison (only if operand parses as Go duration)
    try:
        nanos = parse_duration(operand)
        alternatives.append(L('cmp_dur', (cmp, nanos)))
    except (ValueError, TypeError):
        pass
    # 2. quantity comparison (only if operand parses as k8s quantity)
    try:
        q = Quantity.parse(operand)
        milli = q.value * 1000
        if milli.denominator == 1:
            alternatives.append(L('cmp_qty', (cmp, int(milli))))
        # sub-milli operands skip the quantity alternative; strings that
        # parse as quantities still hit the wildcard/string alternative
    except ValueError:
        pass
    # 3. wildcard string comparison (only for == / !=)
    if cmp in (CMP_EQ, CMP_NE):
        str_check = _compile_wildcard_eq(slot, operand)
        if cmp == CMP_NE:
            str_check = BoolExpr.all([
                BoolExpr.of(Leaf(slot, 'convertible')),
                BoolExpr.negate(str_check)])
        alternatives.append(str_check)
    if not alternatives:
        raise CompileError(f'no vectorizable interpretation for {term!r}')
    return BoolExpr.any(alternatives)


def _compile_wildcard_eq(slot: Slot, operand: str) -> BoolExpr:
    """Classify a wildcard pattern into a vectorizable string class
    (shared classification: ir.classify_wildcard)."""
    from .ir import classify_wildcard

    def L(op, loperand=None):
        return BoolExpr.of(Leaf(slot, op, loperand))

    if len(operand.encode()) > STR_LEN:
        raise CompileError('operand longer than encoded string window')
    kind, parts = classify_wildcard(operand)
    if kind == 'eq':
        return L('eq_str', operand)
    if kind == 'any':
        return L('any_str')
    if kind == 'nonempty':
        return L('nonempty')
    if kind == 'prefix':
        return L('prefix', parts[0])
    if kind == 'suffix':
        return L('suffix', parts[0])
    if kind == 'prefix_suffix':
        # "a*b": prefix a AND suffix b AND len >= len(a)+len(b)
        return BoolExpr.all([
            L('prefix', parts[0]), L('suffix', parts[1]),
            L('min_len',
              len(parts[0].encode()) + len(parts[1].encode()))])
    # general wildcard: DP over the byte window (exact when the value fits
    # the window; else → unknown → host)
    return L('wildcard', operand)


# ---------------------------------------------------------------------------
# Condition compilation (deny / preconditions)

# the deprecated In/NotIn have enough extra quirks (strict string slices,
# _set_in json semantics) that they stay host-side
_SUPPORTED_COND_OPS = {
    'equal', 'equals', 'notequal', 'notequals',
    'anyin', 'allin', 'anynotin', 'allnotin',
    'greaterthanorequals', 'greaterthan', 'lessthanorequals', 'lessthan',
}


def _compile_conditions(cps: CompiledPolicySet, conditions: Any,
                        elem_list_expr: Optional[str] = None,
                        err_gathers: Optional[List] = None) -> BoolExpr:
    """Compile any/all condition blocks to a BoolExpr
    (semantics: kyverno_tpu/engine/operators.py evaluate_conditions).
    With ``elem_list_expr`` set, conditions compile at foreach-element
    scope (either side may be an element variable)."""
    def one(c):
        if elem_list_expr is not None:
            if not isinstance(c, dict):
                raise CompileError('bad condition')
            return _compile_condition_elem(cps, elem_list_expr, c,
                                           err_gathers)
        return _compile_condition(cps, c)

    if conditions is None:
        return BoolExpr.of(Leaf(Slot(()), 'true'))
    if isinstance(conditions, dict):
        return _compile_any_all(cps, conditions, one)
    if isinstance(conditions, list):
        if conditions and all(isinstance(c, dict) and
                              ('any' in c or 'all' in c)
                              for c in conditions):
            return BoolExpr.all([_compile_any_all(cps, c, one)
                                 for c in conditions])
        if not conditions:
            raise CompileError('empty legacy condition list')
        return BoolExpr.all([one(c) for c in conditions])
    raise CompileError('bad conditions shape')


def _compile_any_all(cps: CompiledPolicySet, block: dict, one) -> BoolExpr:
    parts: List[BoolExpr] = []
    any_conditions = block.get('any')
    all_conditions = block.get('all')
    if any_conditions is not None:
        if not isinstance(any_conditions, list):
            raise CompileError('bad any block')
        if not any_conditions:
            # any([]) is False in the host evaluator
            parts.append(BoolExpr.negate(
                BoolExpr.of(Leaf(Slot(()), 'true'))))
        else:
            parts.append(BoolExpr.any([one(c) for c in any_conditions]))
    if all_conditions:
        if not isinstance(all_conditions, list):
            raise CompileError('bad all block')
        parts.append(BoolExpr.all([one(c) for c in all_conditions]))
    if not parts:
        return BoolExpr.of(Leaf(Slot(()), 'true'))
    return BoolExpr.all(parts)


def _compile_condition(cps: CompiledPolicySet, cond: Any) -> BoolExpr:
    if not isinstance(cond, dict):
        raise CompileError('bad condition')
    op = str(cond.get('operator', '')).lower()
    if op not in _SUPPORTED_COND_OPS:
        raise CompileError(f'operator {op!r} not vectorized')
    key = cond.get('key')
    value = cond.get('value')
    _check_constant(value)
    gather, _ = _compile_condition_key(key)
    cps.gather_id(gather)
    return BoolExpr.of_cond(CondCheck(
        gather=gather, op=op, values=_normalize_values(value),
        list_value=isinstance(value, list)))


def _check_constant(value: Any, top: bool = True) -> None:
    """Condition values must be flat, variable-free constants."""
    if isinstance(value, str) and (is_variable(value) or is_reference(value)):
        raise CompileError(f'variable in condition value: {value!r}')
    if isinstance(value, list):
        if not top:
            raise CompileError('nested list condition value not vectorized')
        for v in value:
            _check_constant(v, top=False)
    if isinstance(value, dict):
        raise CompileError('map-typed condition value not vectorized')


def _normalize_values(value: Any) -> Tuple[Any, ...]:
    if isinstance(value, list):
        return tuple(value)
    return (value,)


# JMESPath custom functions whose results vary between evaluations —
# encode-time projection would diverge from a host re-run
_STATEFUL_FN_RE = re.compile(
    r'\b(random|time_now|time_now_utc)\s*\(')


def _compile_condition_key(key: Any) -> Tuple[GatherSlot, bool]:
    """Compile a condition key — a single ``{{ jmespath }}`` — into a
    gather projection.

    The expression is evaluated verbatim at encode time by the in-repo
    JMESPath interpreter against the same ``{'request': {'object': doc}}``
    context the host engine builds for background scans
    (engine/api.py:172-178), so gather semantics are host-exact for ANY
    expression the parser accepts; only stateful functions are barred.
    """
    if not isinstance(key, str):
        raise CompileError('non-string condition key not vectorized')
    m = _SINGLE_VAR_RE.match(key.strip())
    if not m:
        raise CompileError(f'condition key is not a single variable: {key!r}')
    expr = m.group(1).strip()
    if '{{' in expr:
        raise CompileError('nested variables not vectorized')
    if _STATEFUL_FN_RE.search(expr):
        raise CompileError('stateful function in condition key')
    from ..engine.jmespath import compile as jp_compile
    try:
        jp_compile(expr)
    except Exception as e:  # noqa: BLE001 - parser errors → host
        raise CompileError(f'unparseable condition key: {e}')
    return GatherSlot(expr), True


# ---------------------------------------------------------------------------
# foreach compilation (deny-conditions form)

def _compile_foreach(cps: CompiledPolicySet, entries: Any) -> StatusExpr:
    """Compile ``validate.foreach`` into per-element condition programs
    (engine.py:611 _validate_foreach, reference: pkg/engine/validation.go:319).

    Supported entry shape: ``list`` + ``deny`` (+ element-scoped
    ``preconditions``); context entries, nested foreach, pattern forms,
    and explicit elementScope fall back to the host."""
    from .ir import ElemGather, ForEachEntryIR
    if not isinstance(entries, list) or not entries:
        raise CompileError('foreach must be a non-empty list')
    ir_entries: List[ForEachEntryIR] = []
    for entry in entries:
        if not isinstance(entry, dict):
            raise CompileError('bad foreach entry')
        if entry.get('context'):
            raise CompileError('foreach context entries not vectorized')
        for k in ('foreach', 'pattern', 'anyPattern', 'podSecurity'):
            if entry.get(k) is not None:
                raise CompileError(f'foreach {k} not vectorized')
        if entry.get('elementScope'):
            raise CompileError('explicit elementScope not vectorized')
        if entry.get('deny') is None:
            raise CompileError('foreach entry without deny')
        list_expr = entry.get('list') or ''
        if not isinstance(list_expr, str) or not list_expr.strip():
            raise CompileError('foreach entry without list')
        list_expr = list_expr.strip()
        if _STATEFUL_FN_RE.search(list_expr):
            raise CompileError('stateful function in foreach list')
        from ..engine.jmespath import compile as jp_compile
        try:
            jp_compile(list_expr)
        except Exception as e:  # noqa: BLE001
            raise CompileError(f'unparseable foreach list: {e}')
        list_gather = GatherSlot(list_expr)
        cps.gather_id(list_gather)

        err_gathers: List[ElemGather] = []
        precond = None
        if entry.get('preconditions') is not None:
            precond = _compile_conditions(
                cps, entry['preconditions'],
                elem_list_expr=list_expr, err_gathers=err_gathers)
        deny = _compile_conditions(
            cps, (entry['deny'] or {}).get('conditions'),
            elem_list_expr=list_expr, err_gathers=err_gathers)
        ir_entries.append(ForEachEntryIR(
            list_gather=list_gather, precond=precond, deny=deny,
            err_gathers=tuple(err_gathers)))
    return StatusExpr('foreach', operand=tuple(ir_entries))


def _compile_condition_elem(cps: CompiledPolicySet, list_expr: str,
                            cond: dict, err_gathers: List) -> BoolExpr:
    """Compile one foreach condition: either side may be an element-scoped
    variable (exactly one side; both-constant folds at compile time)."""
    from ..engine import operators as host_ops
    from .ir import ElemGather
    op = str(cond.get('operator', '')).lower()
    key = cond.get('key')
    value = cond.get('value')
    key_var = isinstance(key, str) and \
        _SINGLE_VAR_RE.match(key.strip()) is not None
    value_var = isinstance(value, str) and \
        _SINGLE_VAR_RE.match(value.strip()) is not None

    def elem_gather(expr_str: str) -> 'ElemGather':
        m = _SINGLE_VAR_RE.match(expr_str.strip())
        expr = m.group(1).strip()
        if '{{' in expr:
            raise CompileError('nested variables not vectorized')
        if _STATEFUL_FN_RE.search(expr):
            raise CompileError('stateful function in condition')
        from ..engine.jmespath import compile as jp_compile
        try:
            jp_compile(expr)
        except Exception as e:  # noqa: BLE001
            raise CompileError(f'unparseable condition expr: {e}')
        eg = ElemGather(list_expr, expr)
        cps.elem_gather_id(eg)
        err_gathers.append(eg)
        return eg

    if key_var and not value_var:
        if op not in _SUPPORTED_COND_OPS:
            raise CompileError(f'operator {op!r} not vectorized')
        _check_constant(value)
        return BoolExpr.of_cond(CondCheck(
            gather=elem_gather(key), op=op, values=_normalize_values(value),
            list_value=isinstance(value, list)))
    if value_var and not key_var:
        if op not in ('equal', 'equals', 'notequal', 'notequals',
                      'anyin', 'allin', 'anynotin', 'allnotin'):
            raise CompileError(f'operator {op!r} not vectorized for '
                               'variable values')
        if isinstance(key, str) and (is_variable(key) or is_reference(key)):
            raise CompileError('partial-variable key not vectorized')
        if isinstance(key, (list, dict)):
            raise CompileError('non-scalar key with variable value not '
                               'vectorized')
        _check_constant(key)
        return BoolExpr.of_cond(CondCheck(
            gather=None, op=op, key_const=key,
            value_gather=elem_gather(value)))
    if not key_var and not value_var:
        # both sides constant: fold through the host operators
        if isinstance(key, str) and (is_variable(key) or is_reference(key)):
            raise CompileError('partial-variable key not vectorized')
        _check_constant(key)
        _check_constant(value)
        handler = host_ops._HANDLERS.get(op)
        if handler is None:
            raise CompileError(f'unknown operator {op!r}')
        result = handler(key, value)
        const = BoolExpr.of(Leaf(Slot(()), 'true'))
        return const if result else BoolExpr.negate(const)
    raise CompileError('variables on both condition sides not vectorized')
