"""Pod Security Standards → device check library.

Compiles ``validate.podSecurity`` rules into slot predicates mirroring
the native check set (kyverno_tpu/pss/checks.py, reference:
pkg/pss/evaluate.go:17 + k8s.io/pod-security-admission DefaultChecks).
Each check becomes a BoolExpr whose truth means "check passes"; the rule
status is the conjunction walked in DEFAULT_CHECKS order, so the first
failing check decides (messages for failures are materialized by the
host engine — only the PASS verdict is synthesized on device).

The pod spec prefix is derived from the rule's matched kinds
(pss/evaluate.py extract_pod_spec, reference: pkg/engine/validation.go:481):
Pod → the resource itself; template workloads → ``spec.template``;
CronJob → ``spec.jobTemplate.spec.template``.  Autogen has already split
rules per kind class, so a compilable rule maps to exactly one prefix.

Two checks scan map keys (AppArmor annotations, volume type keys), which
the slot model cannot address; those use *virtual gathers* — encoder-side
Python closures marked ``__pss:...`` that project a boolean per resource
(host-exact by construction, still ~50× cheaper than a full host run).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from ..pss.checks import (_ALLOWED_SELINUX_TYPES, _ALLOWED_SYSCTLS,
                          _BASELINE_CAPS, LEVEL_BASELINE)
from .ir import (BoolExpr, CompileError, CompiledPolicySet, CondCheck,
                 GatherSlot, Leaf, Slot, StatusExpr)

_TEMPLATE_PREFIX: dict = {
    'Pod': (),
    'DaemonSet': ('spec', 'template'),
    'Deployment': ('spec', 'template'),
    'Job': ('spec', 'template'),
    'StatefulSet': ('spec', 'template'),
    'ReplicaSet': ('spec', 'template'),
    'ReplicationController': ('spec', 'template'),
    'CronJob': ('spec', 'jobTemplate', 'spec', 'template'),
}


def _rule_kinds(rule: dict) -> List[str]:
    kinds: List[str] = []
    match = rule.get('match') or {}
    for f in [match] + (match.get('any') or []) + (match.get('all') or []):
        for k in (f.get('resources') or {}).get('kinds') or []:
            kinds.append(str(k).split('/')[-1])
    return kinds


def compile_pod_security(cps: CompiledPolicySet, pod_security: dict,
                         rule: dict) -> StatusExpr:
    if pod_security.get('exclude'):
        raise CompileError('podSecurity excludes require the host engine')
    from ..pss.evaluate import parse_version
    try:
        level, _version = parse_version(pod_security)
    except ValueError:
        raise CompileError('invalid podSecurity version')
    kinds = _rule_kinds(rule)
    if not kinds:
        raise CompileError('podSecurity rule without kinds')
    prefixes = set()
    for kind in kinds:
        if kind not in _TEMPLATE_PREFIX:
            raise CompileError(f'podSecurity kind {kind!r} not mapped')
        prefixes.add(_TEMPLATE_PREFIX[kind])
    if len(prefixes) != 1:
        raise CompileError('podSecurity rule spans multiple pod prefixes')
    prefix = next(iter(prefixes))

    b = _Builder(cps, prefix)
    checks: List[Tuple[str, BoolExpr]] = [
        ('hostNamespaces', b.host_namespaces()),
        ('privileged', b.privileged()),
        ('capabilities_baseline', b.capabilities_baseline()),
        ('hostPathVolumes', b.host_path_volumes()),
        ('hostPorts', b.host_ports()),
        ('appArmorProfile', b.app_armor()),
        ('seLinuxOptions', b.selinux_options()),
        ('procMount', b.proc_mount()),
        ('seccompProfile_baseline', b.seccomp_baseline()),
        ('sysctls', b.sysctls()),
        ('windowsHostProcess', b.windows_host_process()),
    ]
    if level != LEVEL_BASELINE:
        checks += [
            ('restrictedVolumes', b.restricted_volumes()),
            ('allowPrivilegeEscalation', b.allow_privilege_escalation()),
            ('runAsNonRoot', b.run_as_non_root()),
            ('runAsUser', b.run_as_user()),
            ('seccompProfile_restricted', b.seccomp_restricted()),
            ('capabilities_restricted', b.capabilities_restricted()),
        ]
    # DEFAULT_CHECKS order: first failing check decides; the host
    # materializes the exact forbidden-reason message on any non-pass
    return StatusExpr.seq(
        [StatusExpr('leaf', expr=e) for _, e in checks])


class _Builder:
    """Per-prefix expression builders, one per check in pss/checks.py."""

    _CONTAINER_FIELDS = ('containers', 'initContainers',
                         'ephemeralContainers')

    def __init__(self, cps: CompiledPolicySet, prefix: Tuple[str, ...]):
        self.cps = cps
        self.prefix = prefix
        self.spec = prefix + ('spec',)
        self.meta = prefix + ('metadata',)

    def _slot(self, path: Tuple[str, ...]) -> Slot:
        slot = Slot(path)
        self.cps.slot_id(slot)
        return slot

    def L(self, path: Tuple[str, ...], op: str, operand: Any = None
          ) -> BoolExpr:
        return BoolExpr.of(Leaf(self._slot(path), op, operand))

    def eq_any(self, path: Tuple[str, ...], values) -> BoolExpr:
        return BoolExpr.any([self.L(path, 'eq_str', v) for v in values])

    def quant(self, kind: str, array: Tuple[str, ...],
              fn: Callable[[Tuple[str, ...]], BoolExpr]) -> BoolExpr:
        slot = self._slot(array)
        return BoolExpr(kind, children=(fn(array + ('*',)),), slot=slot)

    def all_containers(self, fn: Callable[[Tuple[str, ...]], BoolExpr],
                       include_ephemeral: bool = True) -> BoolExpr:
        fields = self._CONTAINER_FIELDS if include_ephemeral else \
            self._CONTAINER_FIELDS[:2]
        return BoolExpr.all([
            self.quant('all_elem', self.spec + (f,), fn) for f in fields])

    def virtual(self, check: str) -> BoolExpr:
        """True when the virtual projection reports a violation."""
        expr = f'__pss:{check}:' + '.'.join(self.prefix)
        gather = GatherSlot(expr)
        self.cps.gather_id(gather)
        return BoolExpr.of_cond(CondCheck(
            gather=gather, op='equals', values=(True,), list_value=False))

    # -- baseline ---------------------------------------------------------

    def host_namespaces(self) -> BoolExpr:
        return BoolExpr.negate(BoolExpr.any([
            self.L(self.spec + (k,), 'truthy')
            for k in ('hostNetwork', 'hostPID', 'hostIPC')]))

    def privileged(self) -> BoolExpr:
        return self.all_containers(lambda c: BoolExpr.negate(
            self.L(c + ('securityContext', 'privileged'), 'is_true')))

    def capabilities_baseline(self) -> BoolExpr:
        caps = sorted(_BASELINE_CAPS)
        return self.all_containers(lambda c: self.quant(
            'all_elem', c + ('securityContext', 'capabilities', 'add'),
            lambda e: self.eq_any(e, caps)))

    def host_path_volumes(self) -> BoolExpr:
        return self.quant(
            'all_elem', self.spec + ('volumes',),
            lambda v: self.L(v + ('hostPath',), 'absent'))

    def host_ports(self) -> BoolExpr:
        return self.all_containers(lambda c: self.quant(
            'all_elem', c + ('ports',),
            lambda p: BoolExpr.negate(self.L(p + ('hostPort',), 'truthy'))))

    def app_armor(self) -> BoolExpr:
        return BoolExpr.negate(self.virtual('apparmor'))

    def selinux_options(self) -> BoolExpr:
        def ok(sc: Tuple[str, ...]) -> BoolExpr:
            opts = sc + ('seLinuxOptions',)
            # opts.get('type', '') — missing → '' (allowed); an explicit
            # null is NOT defaulted and violates (checks.py:160)
            type_ok = BoolExpr.any(
                [self.L(opts + ('type',), 'absent'),
                 self.L(opts + ('type',), 'eq_str', ''),
                 self.eq_any(opts + ('type',),
                             sorted(t for t in _ALLOWED_SELINUX_TYPES if t))])
            no_user = BoolExpr.negate(self.L(opts + ('user',), 'truthy'))
            no_role = BoolExpr.negate(self.L(opts + ('role',), 'truthy'))
            return BoolExpr.all([type_ok, no_user, no_role])
        return BoolExpr.all(
            [ok(self.spec + ('securityContext',))] +
            [self.all_containers(
                lambda c: ok(c + ('securityContext',)))])

    def proc_mount(self) -> BoolExpr:
        def ok(c: Tuple[str, ...]) -> BoolExpr:
            pm = c + ('securityContext', 'procMount')
            return BoolExpr.any([
                BoolExpr.negate(self.L(pm, 'truthy')),
                self.L(pm, 'eq_str', 'Default')])
        return self.all_containers(ok)

    def seccomp_baseline(self) -> BoolExpr:
        def ok(sc: Tuple[str, ...]) -> BoolExpr:
            return BoolExpr.negate(self.L(
                sc + ('securityContext', 'seccompProfile', 'type'),
                'eq_str', 'Unconfined'))
        pod_ok = BoolExpr.negate(self.L(
            self.spec + ('securityContext', 'seccompProfile', 'type'),
            'eq_str', 'Unconfined'))
        return BoolExpr.all([pod_ok, self.all_containers(ok)])

    def sysctls(self) -> BoolExpr:
        return self.quant(
            'all_elem', self.spec + ('securityContext', 'sysctls'),
            lambda s: self.eq_any(s + ('name',), sorted(_ALLOWED_SYSCTLS)))

    def windows_host_process(self) -> BoolExpr:
        wo = ('securityContext', 'windowsOptions', 'hostProcess')
        pod_ok = BoolExpr.negate(self.L(self.spec + wo, 'is_true'))
        return BoolExpr.all([pod_ok, self.all_containers(
            lambda c: BoolExpr.negate(self.L(c + wo, 'is_true')))])

    # -- restricted -------------------------------------------------------

    def restricted_volumes(self) -> BoolExpr:
        return BoolExpr.negate(self.virtual('volumes'))

    def allow_privilege_escalation(self) -> BoolExpr:
        return self.all_containers(lambda c: self.L(
            c + ('securityContext', 'allowPrivilegeEscalation'), 'is_false'))

    def run_as_non_root(self) -> BoolExpr:
        pod = self.spec + ('securityContext', 'runAsNonRoot')
        pod_false = self.L(pod, 'is_false')
        pod_true = self.L(pod, 'is_true')
        no_false = self.all_containers(lambda c: BoolExpr.negate(self.L(
            c + ('securityContext', 'runAsNonRoot'), 'is_false')))
        # a container with the setting unset (None) violates unless the
        # pod-level default is exactly True (pss/checks.py:297)
        any_unset = BoolExpr.any([
            self.quant('any_elem', self.spec + (f,),
                       lambda c: _nullish(self, c + (
                           'securityContext', 'runAsNonRoot')))
            for f in self._CONTAINER_FIELDS])
        return BoolExpr.all([
            BoolExpr.negate(pod_false),
            no_false,
            BoolExpr.any([BoolExpr.negate(any_unset), pod_true]),
        ])

    def run_as_user(self) -> BoolExpr:
        pod_ok = BoolExpr.negate(self.L(
            self.spec + ('securityContext', 'runAsUser'), 'is_zero_num'))
        return BoolExpr.all([pod_ok, self.all_containers(
            lambda c: BoolExpr.negate(self.L(
                c + ('securityContext', 'runAsUser'), 'is_zero_num')))])

    def seccomp_restricted(self) -> BoolExpr:
        allowed = ('Localhost', 'RuntimeDefault')
        pod_path = self.spec + ('securityContext', 'seccompProfile', 'type')
        pod_ok = self.eq_any(pod_path, allowed)
        def c_ok(c: Tuple[str, ...]) -> BoolExpr:
            ct = c + ('securityContext', 'seccompProfile', 'type')
            explicit_ok = self.eq_any(ct, allowed)
            inherits = _nullish(self, ct)
            return BoolExpr.any([
                explicit_ok,
                BoolExpr.all([inherits, pod_ok])])
        return self.all_containers(c_ok)

    def capabilities_restricted(self) -> BoolExpr:
        def c_ok(c: Tuple[str, ...]) -> BoolExpr:
            caps = c + ('securityContext', 'capabilities')
            drops_all = self.quant('any_elem', caps + ('drop',),
                                   lambda e: self.L(e, 'eq_str', 'ALL'))
            adds_ok = self.quant('all_elem', caps + ('add',),
                                 lambda e: self.L(e, 'eq_str',
                                                  'NET_BIND_SERVICE'))
            return BoolExpr.all([drops_all, adds_ok])
        return self.all_containers(c_ok, include_ephemeral=False)


def _nullish(b: _Builder, path: Tuple[str, ...]) -> BoolExpr:
    """`.get(key) is None` — key absent or explicitly null."""
    slot = b._slot(path)
    return BoolExpr.negate(BoolExpr.of(Leaf(slot, 'star')))


# ---------------------------------------------------------------------------
# virtual gathers (encoder-side projections for map-key scans)

class _VirtualSearcher:
    def __init__(self, fn: Callable[[dict], bool],
                 prefix: Tuple[str, ...]):
        self._fn = fn
        self._prefix = prefix

    def search(self, data: dict) -> bool:
        doc = (data.get('request') or {}).get('object') or {}
        for part in self._prefix:
            doc = doc.get(part) if isinstance(doc, dict) else None
            if doc is None:
                doc = {}
                break
        return self._fn(doc if isinstance(doc, dict) else {})


def _apparmor_violation(pod: dict) -> bool:
    from ..pss.checks import check_app_armor
    return not check_app_armor(pod.get('metadata') or {},
                               pod.get('spec') or {}).allowed


def _volumes_violation(pod: dict) -> bool:
    from ..pss.checks import check_restricted_volumes
    return not check_restricted_volumes(pod.get('metadata') or {},
                                        pod.get('spec') or {}).allowed


_VIRTUALS = {'apparmor': _apparmor_violation, 'volumes': _volumes_violation}


def virtual_searcher(expr: str) -> _VirtualSearcher:
    """Resolve a ``__pss:<check>:<dotted-prefix>`` virtual gather."""
    _, check, dotted = expr.split(':', 2)
    prefix = tuple(p for p in dotted.split('.') if p)
    return _VirtualSearcher(_VIRTUALS[check], prefix)
