"""Pod Security Standards → device rule library (placeholder this commit).

Will compile ``validate.podSecurity`` rules into the gather/condition
vocabulary (reference: pkg/pss/evaluate.go); until then PSS rules fall
back to the host evaluator.
"""

from __future__ import annotations

from .ir import CompileError, CompiledPolicySet, StatusExpr


def compile_pod_security(cps: CompiledPolicySet,
                         pod_security: dict) -> StatusExpr:
    raise CompileError('podSecurity device library not yet enabled')
