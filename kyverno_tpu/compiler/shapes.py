"""Canonical batch-shape table: the few row capacities XLA ever sees.

XLA compiles one executable per distinct input shape, and every
compiled shape is a warm-up liability: a fresh process pays one
deserialize-or-compile per shape before it serves (BENCH r03-r05
measured the power-of-two bucket ladder at 49-93s of warm against ~28s
of actual scan).  This module replaces that ladder with a *canonical
capacity table* — by default just ``{KTPU_SMALL_BATCH, KTPU_SCAN_CHUNK}``
— so a policy set compiles at most two row shapes, ever:

* batches at or below the small capacity pad to it (the admission
  shape; runs on the host-local CPU backend);
* everything else pads to the chunk capacity (the bulk-scan shape;
  multi-chunk scans stream it).

The evaluator takes the row count along with the tensors (the
``__rowvalid__`` lane emitted by ``encode_batch``) and masks the tail
rows inside the jitted program, so occupancy is ragged while the
compiled shape stays fixed — the Ragged Paged Attention trick applied
to policy batches.  ``KTPU_CANONICAL_CAPS`` inserts extra capacities
(e.g. ``64,1024,16384``) for deployments whose mid-size rescans are
transfer-bound; every entry is one more executable to warm.

ktpu-lint KTPU204 flags any ``encode_batch`` / ``encode_mutate_batch``
call whose ``padded_n`` is not derived from this table, so the bucket
zoo cannot silently regrow.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def canonical_caps(chunk: Optional[int] = None,
                   small: Optional[int] = None) -> Tuple[int, ...]:
    """The ascending canonical capacity table.

    ``KTPU_CANONICAL_CAPS`` (comma-separated row counts), when set, is
    the whole table; otherwise the table is ``{small, chunk}``.
    Callers with their own chunk/small configuration (``BatchScanner``
    passes its class attributes) thread it through so a monkeypatched
    scanner and this table can never disagree."""
    raw = os.environ.get('KTPU_CANONICAL_CAPS', '')
    if raw.strip():
        try:
            caps = sorted({int(x) for x in raw.split(',') if x.strip()})
            if caps and all(c > 0 for c in caps):
                return tuple(caps)
        except ValueError:
            pass
    if chunk is None:
        chunk = _env_int('KTPU_SCAN_CHUNK', 16384)
    if small is None:
        small = _env_int('KTPU_SMALL_BATCH', 64)
    return tuple(sorted({max(small, 1), max(chunk, 1)}))


def canonical_capacity(n: int, chunk: Optional[int] = None,
                       small: Optional[int] = None,
                       caps: Optional[Sequence[int]] = None) -> int:
    """Smallest canonical capacity holding ``n`` rows (callers chunk
    batches larger than the biggest capacity, so the top entry also
    serves as the spill shape)."""
    table = tuple(caps) if caps is not None else \
        canonical_caps(chunk=chunk, small=small)
    for cap in table:
        if n <= cap:
            return cap
    return table[-1]


def small_capacity(small: Optional[int] = None) -> int:
    """The admission-serving capacity (the table's smallest entry)."""
    return canonical_caps(small=small)[0]
