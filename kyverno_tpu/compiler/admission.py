"""Per-row admission lanes: subject/role match decided in-graph.

Batched admission serving historically required every rider of a shared
dispatch to carry an IDENTICAL admission tuple (userInfo / roles /
namespace labels / operation), because the host match sieve evaluated
one scan-wide tuple.  Real traffic — millions of distinct users — then
degenerates to batch-of-one.  This module moves the per-request
variation into tensor lanes, the same trick the ragged batch kernels
play with row counts: the batch key collapses to the policy set, and
one compiled program serves arbitrary request mixes.

Three pieces, mirroring the encode.py / ops/eval.py split:

* **compile** (:func:`compile_admission`): for every compiled program
  whose match/exclude depends on admission data (roles / clusterRoles /
  subjects) and whose resource descriptions are group-simple
  (kinds/namespaces/operations — cacheable per resource group), lower
  the rule's filter structure to a static boolean tree over per-filter
  atoms.  Operand strings are **interned exactly** into a per-policy-set
  vocabulary, so device membership tests are integer-id equality — no
  hashing, no collision risk, bit-identity preserved by construction.
  Rules outside this vocabulary (namespaceSelector, selector+userinfo
  combinations, non-list operands) simply keep the host matcher.
* **row encoding** (:func:`encode_rows`): each request's admission tuple
  becomes fixed-width int32 id lanes (username, groups, RBAC roles,
  cluster roles) plus ``hasinfo``/``excluded`` flags.  A row whose
  values do not intern exactly (non-string entries, more in-vocabulary
  values than the lane width) is marked *unencodable*: that row alone
  falls back to the host matcher under the coverage-taxonomy reason
  ``admission_unencodable`` — it never holds the rest of the batch.
* **host halves**: :func:`atom_ok` evaluates one filter's
  resource-shape atom with the exact host helpers (group-cached by the
  scanner), and :func:`match_upper` derives the conservative
  over-approximation the fail-detail compaction mask uses before the
  device's exact decision lands.

The in-graph decision itself lives in ``ops/eval.py``
(``_adm_match_graph``), which consumes these tables and lanes inside
the same jitted evaluator — admission lanes add inputs, not
executables, so the fresh-process census stays at
``WARM_EXECUTABLES_MAX``.  ``KTPU_ADM_LANES=0`` disables the whole
mechanism (every admission-dependent match stays on the host matcher,
the bit-identity oracle).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

#: fixed per-row lane widths (static shapes: widths are part of the
#: compiled signature, so they are constants, not knobs).  Rows with
#: more *in-vocabulary* values than a lane holds are unencodable and
#: fall back per-row; out-of-vocabulary values can never match any
#: operand and are dropped before the width check.
GROUPS_W = 16
ROLES_W = 16

#: lane-name contract shared with ops/eval.py and compiler/scan.py
LANE_NAMES = ('__admres__', '__adm_user__', '__adm_groups__',
              '__adm_roles__', '__adm_croles__', '__adm_hasinfo__',
              '__adm_excluded__')

#: resource-description keys whose match decision is a function of the
#: (kind, apiVersion, namespace) group alone (the matcher ignores
#: ``operations`` entirely) — the same set compiler/scan.py group-caches
_SIMPLE_RES_KEYS = frozenset({'kinds', 'namespaces', 'operations'})


def lanes_enabled() -> bool:
    return os.environ.get('KTPU_ADM_LANES', '1') not in ('0', 'false',
                                                         'off')


class AdmFilter(NamedTuple):
    """One lowered match/exclude filter: a resource-shape atom index
    plus exact-interned user-info operand id sets.  ``has_*`` flags
    capture host presence semantics (a ``roles`` list whose entries all
    failed to intern still *gates* — it can only ever match via the
    excluded-groups escape)."""
    atom: int
    has_res: bool
    has_roles: bool
    has_croles: bool
    has_subjects: bool
    roles: Tuple[int, ...]
    cluster_roles: Tuple[int, ...]
    subjects_ug: Tuple[int, ...]   # User/Group names vs groups+username
    subjects_sa: Tuple[int, ...]   # full system:serviceaccount:ns:name

    @property
    def has_ui(self) -> bool:
        return self.has_roles or self.has_croles or self.has_subjects


class AdmProgram(NamedTuple):
    """Static filter structure of one eligible program (column ``j`` in
    the compiled program space)."""
    j: int
    match_kind: str                       # 'any' | 'all' | 'plain'
    match_filters: Tuple[AdmFilter, ...]
    exclude_kind: str                     # 'none' | 'any' | 'all' | 'plain'
    exclude_filters: Tuple[AdmFilter, ...]


class AdmAtom(NamedTuple):
    """Host-evaluated resource-shape atom: the policy namespace gate AND
    the filter's (simple) resource description."""
    policy_index: int
    resources: dict


class AdmissionTable(NamedTuple):
    programs: Tuple[AdmProgram, ...]
    atoms: Tuple[AdmAtom, ...]
    vocab: Dict[str, int]

    def program_cols(self) -> np.ndarray:
        return np.array([p.j for p in self.programs], np.int64)


# ---------------------------------------------------------------------------
# compile: rule match/exclude blocks -> static filter trees


def _filters_of(block: dict, mode: str) -> Tuple[str, List[dict]]:
    """Mirror matches_resource_description's filter extraction
    (engine/match.py): any/all lists verbatim, else the plain
    four-field filter; an empty plain exclude never excludes."""
    any_f = block.get('any') or []
    all_f = block.get('all') or []
    if any_f:
        return 'any', list(any_f)
    if all_f:
        return 'all', list(all_f)
    plain = {'resources': block.get('resources') or {},
             'roles': block.get('roles'),
             'clusterRoles': block.get('clusterRoles'),
             'subjects': block.get('subjects')}
    if mode == 'exclude':
        if not any([plain['resources'], plain['roles'],
                    plain['clusterRoles'], plain['subjects']]):
            return 'none', []
    return 'plain', [plain]


def _lower_filter(f: Any, policy_index: int, intern,
                  atoms: List[AdmAtom]) -> Optional[AdmFilter]:
    if not isinstance(f, dict):
        return None
    res = f.get('resources') or {}
    if not isinstance(res, dict) or \
            any(k not in _SIMPLE_RES_KEYS for k in res):
        return None
    roles = f.get('roles') or []
    croles = f.get('clusterRoles') or []
    subjects = f.get('subjects') or []
    if not isinstance(roles, list) or not isinstance(croles, list) or \
            not isinstance(subjects, list):
        # a non-list here changes host semantics ('in' on a string is a
        # substring test) — keep the whole rule on the host matcher
        return None
    role_ids = tuple(sorted({intern(r) for r in roles
                             if isinstance(r, str)}))
    crole_ids = tuple(sorted({intern(r) for r in croles
                              if isinstance(r, str)}))
    ug: set = set()
    sa: set = set()
    for s in subjects:
        if not isinstance(s, dict):
            return None  # the host matcher would raise; stay off device
        kind = s.get('kind', '')
        if kind == 'ServiceAccount':
            # host: username[len('system:serviceaccount:'):] == 'ns:name'
            # — equivalent to full-username equality (the suffix always
            # contains at least the separating colon)
            sa.add(intern('system:serviceaccount:'
                          f"{s.get('namespace', '')}:{s.get('name', '')}"))
        elif kind in ('User', 'Group'):
            nm = s.get('name')
            if isinstance(nm, str):
                ug.add(intern(nm))
            # non-string names can never equal a string user key
        # other kinds never match on the host either: contribute nothing
    atom = len(atoms)
    atoms.append(AdmAtom(policy_index, dict(res)))
    return AdmFilter(atom, bool(res), bool(roles), bool(croles),
                     bool(subjects), role_ids, crole_ids,
                     tuple(sorted(ug)), tuple(sorted(sa)))


def _lower_rule(j: int, rule: dict, policy_index: int, intern,
                atoms: List[AdmAtom]) -> Optional[AdmProgram]:
    match = rule.get('match') or {}
    exclude = rule.get('exclude') or {}
    if not isinstance(match, dict) or not isinstance(exclude, dict):
        return None
    mk, mfs_raw = _filters_of(match, 'match')
    ek, efs_raw = _filters_of(exclude, 'exclude')

    def dep(f) -> bool:
        return isinstance(f, dict) and bool(
            f.get('roles') or f.get('clusterRoles') or f.get('subjects'))

    if not any(dep(f) for f in mfs_raw + efs_raw):
        return None  # admission-invariant: the group cache already serves it
    staged: List[AdmAtom] = []
    mfs = [_lower_filter(f, policy_index, intern, staged) for f in mfs_raw]
    efs = [_lower_filter(f, policy_index, intern, staged) for f in efs_raw]
    if any(f is None for f in mfs + efs):
        return None  # outside the lane vocabulary: host matcher
    base = len(atoms)
    atoms.extend(staged)
    shift = [f._replace(atom=f.atom + base) for f in mfs + efs]
    mfs2, efs2 = shift[:len(mfs)], shift[len(mfs):]
    return AdmProgram(j, mk, tuple(mfs2), ek, tuple(efs2))


def compile_admission(cps) -> Optional[AdmissionTable]:
    """Lower every eligible program of ``cps`` (or None when nothing is
    admission-dependent, or ``KTPU_ADM_LANES`` is off).  Deterministic
    for a policy set, so the table is implicitly covered by the AOT
    fingerprint and the lane signature."""
    if not lanes_enabled():
        return None
    vocab: Dict[str, int] = {}

    def intern(s: str) -> int:
        return vocab.setdefault(s, len(vocab))

    atoms: List[AdmAtom] = []
    programs: List[AdmProgram] = []
    for j, prog in enumerate(cps.programs):
        rule = prog.rule_raw
        if not isinstance(rule, dict):
            continue
        spec = _lower_rule(j, rule, prog.policy_index, intern, atoms)
        if spec is not None:
            programs.append(spec)
    if not programs:
        return None
    return AdmissionTable(tuple(programs), tuple(atoms), vocab)


# ---------------------------------------------------------------------------
# host halves: resource-shape atoms + the compaction upper bound


def atom_ok(atom: AdmAtom, policy, res) -> bool:
    """One filter's resource-shape decision for one resource — the exact
    host helpers the matcher itself runs (_check_resource_description
    with admission-free arguments; group-cacheable: nothing here reads
    beyond kind/apiVersion/namespace and the policy namespace gate)."""
    if policy.is_namespaced and (
            not res.namespace or res.namespace != policy.namespace):
        return False
    if not atom.resources:
        return True
    from ..engine.match import _check_resource_description
    return not _check_resource_description(atom.resources, res, {}, '',
                                           True, None)


def match_upper(table: AdmissionTable, atoms_u8: np.ndarray) -> np.ndarray:
    """[R, n_elig] conservative upper bound of the final match (user
    info treated as always-matching, exclusion as never-excluding) —
    what the device compaction mask may safely use before the exact
    in-graph decision replaces it."""
    n = atoms_u8.shape[0]
    out = np.zeros((n, len(table.programs)), bool)
    for c, p in enumerate(table.programs):
        oks = [atoms_u8[:, f.atom].astype(bool)
               if (f.has_res or f.has_ui) else np.zeros(n, bool)
               for f in p.match_filters]
        if not oks:
            continue
        if p.match_kind == 'all':
            acc = oks[0]
            for o in oks[1:]:
                acc = acc & o
        else:  # 'any' | 'plain'
            acc = oks[0]
            for o in oks[1:]:
                acc = acc | o
        out[:, c] = acc
    return out


# ---------------------------------------------------------------------------
# per-row encoding


class AdmissionRowPlan:
    """Encoded admission lanes + host bookkeeping for one scan.

    ``valid`` marks rows whose device decision is authoritative;
    ``unencodable`` the subset excluded because their admission values
    did not intern exactly (UPDATE rows carrying an oldObject are also
    non-``valid`` — their old-match retry folds on the host — but that
    is a semantic exclusion, not a taxonomy event)."""

    __slots__ = ('lanes', 'valid', 'unencodable', 'upper')

    def __init__(self, lanes: Dict[str, np.ndarray], valid: np.ndarray,
                 unencodable: np.ndarray):
        self.lanes = lanes
        self.valid = valid
        self.unencodable = unencodable
        self.upper: Optional[np.ndarray] = None


def _str_list(v) -> Optional[List[str]]:
    if v is None:
        return []
    if not isinstance(v, (list, tuple)) or \
            any(not isinstance(x, str) for x in v):
        return None
    return list(v)


def encode_rows(table: AdmissionTable, adm_rows: List[Any],
                old_flags: Optional[List[bool]] = None
                ) -> AdmissionRowPlan:
    """Encode one admission tuple per row into the fixed-width id lanes.

    ``adm_rows[i]`` is the (admission_info, exclude_group_roles,
    namespace_labels, operation) tuple webhook scans thread through.
    Interning is exact: a value outside the vocabulary becomes -1 and
    can never match an operand, so equality on ids IS equality on
    strings."""
    n = len(adm_rows)
    user = np.full(n, -1, np.int32)
    groups = np.full((n, GROUPS_W), -1, np.int32)
    roles = np.full((n, ROLES_W), -1, np.int32)
    croles = np.full((n, ROLES_W), -1, np.int32)
    hasinfo = np.zeros(n, np.int8)
    excluded = np.zeros(n, np.int8)
    valid = np.zeros(n, bool)
    unenc = np.zeros(n, bool)
    vocab = table.vocab
    for i, adm in enumerate(adm_rows):
        if not isinstance(adm, tuple) or len(adm) < 2:
            unenc[i] = True
            continue
        info, egr = adm[0], adm[1]
        if info is not None and not isinstance(info, dict):
            unenc[i] = True
            continue
        info = info or {}
        ui = info.get('userInfo') or {}
        if not isinstance(ui, dict):
            unenc[i] = True
            continue
        username = ui.get('username', '') or ''
        g = _str_list(ui.get('groups'))
        r = _str_list(info.get('roles'))
        cr = _str_list(info.get('clusterRoles'))
        ex = _str_list(egr)
        if not isinstance(username, str) or None in (g, r, cr, ex):
            unenc[i] = True
            continue
        gid = sorted({vocab[x] for x in g if x in vocab})
        rid = sorted({vocab[x] for x in r if x in vocab})
        cid = sorted({vocab[x] for x in cr if x in vocab})
        if len(gid) > GROUPS_W or len(rid) > ROLES_W or \
                len(cid) > ROLES_W:
            unenc[i] = True
            continue
        user[i] = vocab.get(username, -1)
        groups[i, :len(gid)] = gid
        roles[i, :len(rid)] = rid
        croles[i, :len(cid)] = cid
        hasinfo[i] = 1 if info else 0
        exset = set(ex)
        excluded[i] = 1 if any(k in exset for k in g + [username]) else 0
        valid[i] = True
    if old_flags is not None:
        # UPDATE rows fold their old-object match retry on the host
        valid &= ~np.asarray(old_flags, bool)
    lanes = {'__adm_user__': user, '__adm_groups__': groups,
             '__adm_roles__': roles, '__adm_croles__': croles,
             '__adm_hasinfo__': hasinfo, '__adm_excluded__': excluded}
    return AdmissionRowPlan(lanes, valid, unenc)


def slice_lanes(lanes: Dict[str, np.ndarray], start: int, ln: int,
                padded: int) -> Dict[str, np.ndarray]:
    """One chunk's lane slice, padded to the canonical capacity (id
    lanes pad with -1 so padding rows can never match an operand)."""
    out: Dict[str, np.ndarray] = {}
    for name, arr in lanes.items():
        part = arr[start:start + ln]
        if padded > part.shape[0]:
            fill = -1 if arr.dtype == np.int32 else 0
            pad = np.full((padded - part.shape[0],) + arr.shape[1:],
                          fill, arr.dtype)
            part = np.concatenate([part, pad])
        out[name] = part
    return out


def zero_lanes(table: AdmissionTable, padded: int) -> Dict[str, np.ndarray]:
    """The no-admission lane set (background scans, shape warm-up):
    same signature as live traffic so admission lanes never add an XLA
    shape — the device output is simply ignored (no row is ``valid``)."""
    return {
        '__admres__': np.zeros((padded, len(table.atoms)), np.uint8),
        '__adm_user__': np.full(padded, -1, np.int32),
        '__adm_groups__': np.full((padded, GROUPS_W), -1, np.int32),
        '__adm_roles__': np.full((padded, ROLES_W), -1, np.int32),
        '__adm_croles__': np.full((padded, ROLES_W), -1, np.int32),
        '__adm_hasinfo__': np.zeros(padded, np.int8),
        '__adm_excluded__': np.zeros(padded, np.int8),
    }
