"""Bounded overlapped chunk pipeline for the streaming scan path.

The 1M-resource background scan is a classic producer chain —
encode → h2d → device_eval → d2h → assemble — and before this module
it ran as two fat threads (encode, dispatch) with the assembly serial
behind them.  Here each leg is its own worker thread connected by
depth-1 queues, with a global in-flight budget (``KTPU_PIPELINE_DEPTH``
chunk slots, default 2): resources flow through a fixed set of buffers
and the pipeline *backpressures* instead of buffering — a slow d2h leg
stalls intake rather than ballooning RSS, which is the paged/streaming
discipline of Ragged Paged Attention applied to the host side.

Instrumentation rides the existing device-telemetry surface: every
stage span re-parents under the scan's request span and feeds the
ambient :class:`~..observability.device.ScanCapture`, blocked ``put``
time lands on ``kyverno_tpu_scan_backpressure_seconds_total{stage}``,
and the number of resident chunks is exported as the
``kyverno_tpu_scan_pipeline_inflight_chunks`` gauge.  Items leave the
pipeline in submission order (single worker per stage, FIFO queues).

Failure model: a transient stage error is retried per chunk
(``KTPU_STAGE_RETRIES`` attempts beyond the first, exponential
backoff) before surfacing at the consumer; an error that burns the
whole budget is marked ``ktpu_retry_exhausted`` and attributed on the
coverage ledger.  Whenever a chunk dies — terminal stage error, or the
stream aborting with chunks still in flight — the ``cleanup`` hook
runs on that chunk's current value, so owners of pooled buffers (the
scanner's encode arena) reclaim them instead of leaking per crash.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple


def pipeline_depth(default: int = 2) -> int:
    """The in-flight chunk budget (``KTPU_PIPELINE_DEPTH``, min 1)."""
    try:
        return max(1, int(os.environ.get('KTPU_PIPELINE_DEPTH',
                                         str(default))))
    except ValueError:
        return default


def stage_retries(default: int = 1) -> int:
    """Retry attempts per (chunk, stage) beyond the first
    (``KTPU_STAGE_RETRIES``, min 0)."""
    try:
        return max(0, int(os.environ.get('KTPU_STAGE_RETRIES',
                                         str(default))))
    except ValueError:
        return default


#: backoff before retry attempt k is ``_RETRY_BACKOFF_S * 2**(k-1)`` —
#: enough for a transient device hiccup to clear, far below the shed
#: deadline of any batched rider waiting on the scan
_RETRY_BACKOFF_S = 0.005


class _Item:
    __slots__ = ('value', 'error', 'seq')

    def __init__(self, value: Any, seq: int = -1):
        self.value = value
        self.seq = seq
        self.error: Optional[BaseException] = None


_SENTINEL = object()


class ChunkPipeline:
    """Run items through named stages on one worker thread per stage.

    ``stages`` is a sequence of ``(name, fn)`` pairs; each ``fn`` maps
    the previous stage's value to the next.  :meth:`run` is a generator
    yielding the final values in submission order; a stage exception
    surfaces at the consumer for the item that failed (later items
    still flow), after ``retries`` transparent re-runs of the failing
    stage on that chunk.  Closing the generator early stops intake and
    drains the workers — no thread outlives the ``run`` call, and
    ``cleanup(value)`` runs for every chunk that errored or was still
    in flight when the stream ended."""

    def __init__(self, stages: Sequence[Tuple[str, Callable[[Any], Any]]],
                 depth: Optional[int] = None, capture=None,
                 parent_span=None,
                 cleanup: Optional[Callable[[Any], None]] = None,
                 retries: Optional[int] = None, timeline=None):
        self.stages = list(stages)
        #: per-scan event recorder (observability/timeline.py
        #: ScanTimeline) — None keeps every hook on its no-cost branch
        self.timeline = timeline
        self.depth = depth if depth is not None else pipeline_depth()
        self.capture = capture
        self.parent_span = parent_span
        self.cleanup = cleanup
        self.retries = retries if retries is not None else stage_retries()
        self._queues: List[queue.Queue] = \
            [queue.Queue(maxsize=1) for _ in self.stages]
        self._out: queue.Queue = queue.Queue()
        self._slots = threading.Semaphore(self.depth)
        self._stop = threading.Event()
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    # -- telemetry ----------------------------------------------------------

    def _track(self, delta: int) -> None:
        from ..observability import device as devtel
        with self._inflight_lock:
            self._inflight += delta
            n = self._inflight
        devtel.set_pipeline_inflight(n)

    def _put(self, q: queue.Queue, stage: str, item) -> None:
        """Queue put with blocked time attributed as backpressure."""
        from ..observability import device as devtel
        try:
            q.put_nowait(item)
            return
        except queue.Full:
            pass
        t0 = time.monotonic()
        q.put(item)
        devtel.add_backpressure(stage, time.monotonic() - t0)
        tl = self.timeline
        if tl is not None and isinstance(item, _Item):
            tl.block(item.seq, stage, t0)

    def _cleanup(self, value: Any) -> None:
        """Best-effort owner cleanup for a chunk that will never reach
        the consumer (terminal stage error or an aborted stream)."""
        if self.cleanup is None or value is None:
            return
        try:
            self.cleanup(value)
        except Exception:  # ktpu: noqa[KTPU304] -- best-effort buffer
            pass           # reclaim; the chunk's own error already surfaced

    def _run_stage(self, name: str, fn: Callable[[Any], Any],
                   item) -> None:
        """Apply one stage to one chunk with the per-chunk retry
        budget; a terminal failure records the exhaustion, releases
        the chunk's buffers, and parks the error on the item for the
        consumer."""
        attempt = 0
        while True:
            try:
                item.value = fn(item.value)
                return
            except BaseException as e:  # noqa: BLE001 - surfaces
                attempt += 1            # at the consumer
                # only plain Exceptions are retry candidates —
                # KeyboardInterrupt/SystemExit must surface immediately
                if attempt <= self.retries and isinstance(e, Exception) \
                        and not self._stop.is_set():
                    t_r = time.monotonic()
                    time.sleep(_RETRY_BACKOFF_S * (2.0 ** (attempt - 1)))
                    if self.timeline is not None:
                        self.timeline.retry(item.seq, name, t_r, attempt)
                    continue
                if attempt > 1:
                    # the whole retry budget burned: mark the error so
                    # shed accounting downstream (batcher quarantine)
                    # can attribute it, and count the attributed fall
                    from ..observability import coverage
                    try:
                        e.ktpu_retry_exhausted = True
                        e.ktpu_stage = name
                    except Exception:  # ktpu: noqa[KTPU304] -- exotic
                        pass           # exception sans __dict__
                    coverage.record_fallback(
                        'serving', coverage.REASON_STAGE_RETRY_EXHAUSTED)
                item.error = e
                self._cleanup(item.value)
                item.value = None
                return

    # -- workers ------------------------------------------------------------

    def _worker(self, i: int) -> None:
        from ..observability import device as devtel
        from ..observability import tracing
        name, fn = self.stages[i]
        qin = self._queues[i]
        qout = self._queues[i + 1] if i + 1 < len(self.stages) else self._out
        next_name = self.stages[i + 1][0] if i + 1 < len(self.stages) \
            else None
        tl = self.timeline
        # worker threads have no ambient span/capture: re-install the
        # scan's so stage spans join the caller's trace and stage time
        # lands on the right provenance record
        with devtel.install_capture(self.capture), \
                tracing.install_span(self.parent_span):
            while True:
                item = qin.get()
                if item is _SENTINEL:
                    qout.put(item)
                    return
                if item.error is None and not self._stop.is_set():
                    if tl is not None:
                        tl.start(item.seq, name)
                    self._run_stage(name, fn, item)
                    if tl is not None:
                        tl.end(item.seq, name, ok=item.error is None)
                self._put(qout, name, item)
                if tl is not None and next_name is not None \
                        and item.error is None:
                    tl.enqueue(item.seq, next_name)

    def _feed(self, items: Iterable) -> None:
        from ..observability import device as devtel
        intake = self._queues[0]
        first_stage = self.stages[0][0] if self.stages else ''
        tl = self.timeline
        try:
            for seq, value in enumerate(items):
                waited = 0.0
                while not self._slots.acquire(timeout=0.05):
                    waited += 0.05
                    if self._stop.is_set():
                        return
                if waited:
                    devtel.add_backpressure('intake', waited)
                    if tl is not None:
                        tl.record('intake', seq,
                                  time.monotonic() - waited, kind='block')
                if self._stop.is_set():
                    self._slots.release()
                    return
                self._track(1)
                if tl is not None:
                    tl.enqueue(seq, first_stage)
                self._put(intake, 'intake', _Item(value, seq))
        finally:
            intake.put(_SENTINEL)

    # -- driver -------------------------------------------------------------

    def run(self, items: Iterable):
        """Yield the fully-processed items in order."""
        threads = [threading.Thread(target=self._worker, args=(i,),
                                    name=f'ktpu-pipe-{name}', daemon=True)
                   for i, (name, _fn) in enumerate(self.stages)]
        feeder = threading.Thread(target=self._feed, args=(items,),
                                  name='ktpu-pipe-intake', daemon=True)
        for t in threads:
            t.start()
        feeder.start()
        try:
            while True:
                item = self._out.get()
                if item is _SENTINEL:
                    return
                self._slots.release()
                self._track(-1)
                if item.error is not None:
                    raise item.error
                yield item.value
        finally:
            self._stop.set()
            feeder.join(timeout=5)
            for t in threads:
                t.join(timeout=5)
            # drain: chunks still parked in the stage queues when the
            # stream ended (consumer raised / generator closed / stage
            # crash) never reach an owner — reclaim their buffers here
            # so an aborted scan leaks nothing
            for q in list(self._queues) + [self._out]:
                while True:
                    try:
                        item = q.get_nowait()
                    except queue.Empty:
                        break
                    if item is _SENTINEL or not isinstance(item, _Item):
                        continue
                    if item.error is None:
                        self._cleanup(item.value)
                        item.value = None
            from ..observability import device as devtel
            with self._inflight_lock:
                self._inflight = 0
            devtel.set_pipeline_inflight(0)
            if self.timeline is not None:
                # workers are joined: close exec intervals a stage had
                # open when the stream was torn down, so the timeline
                # never leaks orphan intervals on early generator close
                self.timeline.close_open()
