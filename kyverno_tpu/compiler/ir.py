"""Compiler IR: policies → slot table + vectorized check programs.

The TPU execution model replaces the reference's per-resource tree-walk
interpreter (reference: pkg/engine/validate/validate.go) with trace-time
specialization:

* a **slot** is a policy-relevant structural path (e.g.
  ``spec.containers.*.image``); resources are *projected* onto the slot
  table at encode time — the document itself never reaches the device
* a **leaf check** is a scalar predicate on one slot, chosen from a closed
  vectorizable vocabulary (string classes, numeric/quantity/duration
  comparisons, existence, bool/null equality)
* a **rule program** is a small boolean tree over leaf checks with
  tri-state (pass/fail/skip) element semantics mirroring the anchor rules
* anything outside the vocabulary is compiled to HOST_FALLBACK and runs on
  the host engine; the device result for such rules is ignored

Because programs are Python constants closed over by the jitted evaluator,
XLA sees straight-line fused elementwise ops over ``[R, E]`` tensors — no
interpreter loop on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# type tags in the encoded tensors
TAG_MISSING = 0
TAG_NULL = 1
TAG_BOOL = 2
TAG_INT = 3
TAG_FLOAT = 4
TAG_STRING = 5
TAG_MAP = 6
TAG_ARRAY = 7

# maximum string bytes kept per value (suffix-matched strings keep the tail)
STR_LEN = 64
# bytes kept from the end of each string (right-aligned suffix window)
TAIL_LEN = 16
# maximum array elements encoded per element-bearing slot
MAX_ELEMS = 16


@dataclass(frozen=True)
class Slot:
    """A policy-relevant structural path.

    ``path`` is a tuple of keys; ``'*'`` marks an array-of-maps traversal.
    At most one ``'*'`` is supported in the vectorized path (deeper nesting
    falls back to host). ``elem`` is True when the slot has an element
    dimension.
    """
    path: Tuple[str, ...]

    @property
    def elem(self) -> bool:
        return '*' in self.path

    def __str__(self):
        return '.'.join(self.path)


# Leaf-check op vocabulary — the single source of truth; the compiler emits
# exactly these strings and ops/eval.py implements exactly this set.
LEAF_OPS = frozenset({
    'true',         # constant pass
    'absent',       # key missing (X() negation anchors)
    'star',         # "*": key present and non-null
    'any_str',      # wildcard "*" string compare: any string-convertible
    'nonempty',     # "?*": non-empty string form
    'convertible',  # value has a string form (guards NotEqual)
    'eq_bool',      # operand: bool
    'eq_null',
    'eq_int',       # operand: int
    'eq_float',     # operand: float (milli-exact)
    'cmp_qty',      # operand: (cmp, milli int)
    'cmp_dur',      # operand: (cmp, nanos int)
    'eq_str',       # operand: str (exact, ≤ STR_LEN bytes)
    'prefix',       # operand: str (≤ STR_LEN bytes)
    'suffix',       # operand: str (≤ TAIL_LEN bytes)
    'min_len',      # operand: int (byte length lower bound)
})

CMP_GT, CMP_GE, CMP_LT, CMP_LE, CMP_EQ, CMP_NE = '>', '>=', '<', '<=', '==', '!='


@dataclass(frozen=True)
class Leaf:
    """A scalar predicate on a slot."""
    slot: Slot
    op: str
    operand: Any = None
    # missing key fails the check unless the leaf is under an equality
    # anchor (=(key): missing passes) — the compiler folds that in here
    missing_ok: bool = False


@dataclass(frozen=True)
class BoolExpr:
    """AND/OR/NOT tree over leaves (within one element scope)."""
    kind: str                      # 'leaf' | 'and' | 'or' | 'not'
    leaf: Optional[Leaf] = None
    children: Tuple['BoolExpr', ...] = ()

    @staticmethod
    def of(leaf: Leaf) -> 'BoolExpr':
        return BoolExpr('leaf', leaf=leaf)

    @staticmethod
    def all(children: List['BoolExpr']) -> 'BoolExpr':
        if len(children) == 1:
            return children[0]
        return BoolExpr('and', children=tuple(children))

    @staticmethod
    def any(children: List['BoolExpr']) -> 'BoolExpr':
        if len(children) == 1:
            return children[0]
        return BoolExpr('or', children=tuple(children))

    @staticmethod
    def negate(child: 'BoolExpr') -> 'BoolExpr':
        return BoolExpr('not', children=(child,))


@dataclass(frozen=True)
class ElementBlock:
    """Per-element tri-state semantics for one array pattern.

    ``mode='forall'`` (reference: pkg/engine/validate/validate.go:218
    validateArrayOfMaps): per element, if ``condition`` fails → element
    SKIP; else ``constraint`` must hold → else FAIL. Rule-level: any FAIL →
    fail; no FAIL and applyCount==0 with skips → skip. A missing/non-array
    value fails.

    ``mode='exists'`` (reference: pkg/engine/anchor/handlers.go:228
    existence anchor): at least one element must satisfy ``constraint``;
    an empty array fails, a missing key passes.
    """
    array_path: Tuple[str, ...]
    condition: Optional[BoolExpr]   # None = unconditional
    constraint: BoolExpr
    mode: str = 'forall'


@dataclass(frozen=True, eq=False)
class RuleProgram:
    """One compiled rule."""
    policy_name: str
    rule_name: str
    policy_index: int
    rule_index: int
    # scalar (non-element) constraints, all must hold
    scalar: Optional[BoolExpr]
    # map-level conditional anchors: all must hold else rule SKIP
    scalar_condition: Optional[BoolExpr]
    # element blocks (array-of-maps), each contributes tri-state
    elements: Tuple[ElementBlock, ...]
    # static pass message (compile-time constant)
    pass_message: str
    background: bool = True
    # the original rule dict (for host-side match evaluation)
    rule_raw: Optional[dict] = None


@dataclass
class CompiledPolicySet:
    """Output of the compiler for a policy set."""
    slots: List[Slot] = field(default_factory=list)
    slot_index: Dict[Slot, int] = field(default_factory=dict)
    programs: List[RuleProgram] = field(default_factory=list)
    # (policy_index, rule dict, policy) for rules the device cannot evaluate
    host_rules: List[Tuple[int, dict, Any]] = field(default_factory=list)
    # per-policy kind → rule match precomputation inputs
    policies: List[Any] = field(default_factory=list)

    def slot_id(self, slot: Slot) -> int:
        if slot not in self.slot_index:
            self.slot_index[slot] = len(self.slots)
            self.slots.append(slot)
        return self.slot_index[slot]


class CompileError(Exception):
    """Raised when a rule (or part) cannot be vectorized → host fallback."""
