"""Compiler IR v2: policies → slot table + tri-state status programs.

The TPU execution model replaces the reference's per-resource tree-walk
interpreter (reference: pkg/engine/validate/validate.go) with trace-time
specialization:

* a **slot** is a policy-relevant structural path (e.g.
  ``spec.containers.*.image``); resources are *projected* onto the slot
  table at encode time — the document itself never reaches the device.
  Paths may contain up to two ``'*'`` array traversals (e.g.
  ``spec.containers.*.ports.*.hostPort``).
* a **gather slot** collects a flattened list of scalars addressed by a
  JMESPath shape (field chains, ``[]`` flattens, multiselect lists,
  ``keys(@)``, ``|| <literal>`` fallbacks) — the device form of deny /
  precondition condition keys over ``request.object``.
* a **leaf check** is a scalar predicate on one slot from a closed
  vectorizable vocabulary; a **condition check** is one reference
  condition operator applied to a gather slot.
* a **status expression** is a tree mirroring the anchor walk with
  tri-state semantics (PASS / FAIL / SKIP), evaluated under Kleene
  three-valued logic so any undecidable leaf yields UNKNOWN → the rule is
  re-run on the host engine for that resource (exactness is never lost).

Because programs are Python constants closed over by the jitted evaluator,
XLA sees straight-line fused elementwise ops over ``[R]``/``[R, E]``
tensors — no interpreter loop on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# type tags in the encoded tensors
TAG_MISSING = 0
TAG_NULL = 1
TAG_BOOL = 2
TAG_INT = 3
TAG_FLOAT = 4
TAG_STRING = 5
TAG_MAP = 6
TAG_ARRAY = 7

# maximum string bytes kept per value (suffix-matched strings keep the tail)
STR_LEN = 64
# bytes kept from the end of each string (right-aligned suffix window)
TAIL_LEN = 16
# maximum array elements encoded per element-bearing slot dimension
MAX_ELEMS = 16
# maximum elements per gather slot (flattened JMESPath projections)
MAX_GATHER = 32

# device status codes (STATUS_HOST = undecidable on device → host fallback;
# STATUS_SKIP_PRECOND = skipped by preconditions, whose message is the
# static 'preconditions not met'; STATUS_VAR_ERR = a condition variable
# failed to resolve — the host's deterministic substitution-error ERROR,
# message indexed by ``detail`` into RuleProgram.error_messages)
STATUS_PASS, STATUS_FAIL, STATUS_SKIP, STATUS_HOST = 0, 1, 2, 3
STATUS_SKIP_PRECOND = 4
STATUS_VAR_ERR = 5
N_STATUS_CODES = 6


@dataclass(frozen=True)
class Slot:
    """A policy-relevant structural path.

    ``path`` is a tuple of keys; ``'*'`` marks an array-of-maps traversal.
    Up to two ``'*'`` levels are vectorized (deeper nesting falls back to
    host). ``depth`` is the number of element dimensions.
    """
    path: Tuple[str, ...]

    @property
    def depth(self) -> int:
        return sum(1 for p in self.path if p == '*')

    @property
    def elem(self) -> bool:
        return self.depth > 0

    def __str__(self):
        return '.'.join(self.path)


# --- gather programs (JMESPath shapes) -------------------------------------

@dataclass(frozen=True)
class GatherSlot:
    """A scalar-or-list value gathered from the resource document.

    ``expr`` is the raw JMESPath condition key (braces stripped); at
    encode time it is evaluated verbatim by the in-repo JMESPath
    interpreter against the same ``{'request': {'object': doc}}`` context
    the host engine builds, so gather semantics are host-exact by
    construction.  ``__pss:``-prefixed exprs are encoder-side Python
    projections (pss_compile.virtual_searcher).
    """
    expr: str

    def __str__(self):
        return self.expr


@dataclass(frozen=True)
class ElemGather:
    """A per-foreach-element projection: ``expr`` evaluated against the
    element context (``element`` / ``elementIndex`` injected over the
    request, engine/context.py:109 add_element) for each element of the
    ``list_expr`` foreach list.  Lanes are [R, FE, EG] with per-(r, fe)
    kind/count/overflow/notfound metadata."""
    list_expr: str
    expr: str

    def __str__(self):
        return f'{self.list_expr}[]→{self.expr}'


# --- leaf checks ------------------------------------------------------------

# Leaf-check op vocabulary — the single source of truth; the compiler emits
# exactly these strings and ops/eval.py implements exactly this set.
LEAF_OPS = frozenset({
    'true',         # constant pass
    'absent',       # key missing (X() negation anchors)
    'present',      # key exists in parent map (anchor presence tests)
    'star',         # "*": key present and non-null
    'is_map',       # structural guard: value is a map
    'is_array',     # structural guard: value is an array
    'any_str',      # wildcard "*" string compare: any string-convertible
    'nonempty',     # "?*": non-empty string form
    'convertible',  # value has a string form (guards NotEqual)
    'eq_bool',      # operand: bool
    'eq_null',      # null pattern: null/0/"" match (missing treated as null)
    'eq_int',       # operand: int
    'eq_float',     # operand: float (milli-exact)
    'cmp_qty',      # operand: (cmp, milli int)
    'cmp_dur',      # operand: (cmp, nanos int)
    'eq_str',       # operand: str (exact, ≤ STR_LEN bytes)
    'prefix',       # operand: str (≤ STR_LEN bytes)
    'suffix',       # operand: str (≤ TAIL_LEN bytes)
    'min_len',      # operand: int (byte length lower bound)
    'wildcard',     # operand: str pattern with */?; DP over the byte window
    # Python-semantics predicates for the PSS check library (pss_compile):
    'truthy',       # bool(value): non-zero number / non-empty string / True
    'is_true',      # value is True (strict bool identity)
    'is_false',     # value is False
    'is_zero_num',  # value == 0 under Python numerics (0, 0.0, False)
})

CMP_GT, CMP_GE, CMP_LT, CMP_LE, CMP_EQ, CMP_NE = '>', '>=', '<', '<=', '==', '!='


def classify_wildcard(operand: str):
    """Classify a glob pattern into the cheapest vectorizable string op.

    Returns (op, parts) with op ∈ {'eq','any','nonempty','prefix',
    'suffix','prefix_suffix','dp'} — shared by the compiler, the
    evaluator's constant matcher, and the lane-need analysis so all three
    agree on which lanes (and byte widths) a comparison reads.
    """
    has_star = '*' in operand
    has_q = '?' in operand
    if not has_star and not has_q:
        return 'eq', (operand,)
    if operand == '*':
        return 'any', ()
    if operand == '?*':
        return 'nonempty', ()
    if not has_q:
        parts = operand.split('*')
        if len(parts) == 2 and parts[0] and not parts[1]:
            return 'prefix', (parts[0],)
        if len(parts) == 2 and not parts[0] and parts[1] and \
                len(parts[1].encode()) <= TAIL_LEN:
            return 'suffix', (parts[1],)
        if len(parts) == 3 and parts[0] and parts[2] and not parts[1] and \
                len(parts[2].encode()) <= TAIL_LEN:
            return 'prefix_suffix', (parts[0], parts[2])
    return 'dp', (operand,)


@dataclass(frozen=True)
class Leaf:
    """A scalar predicate on a slot."""
    slot: Slot
    op: str
    operand: Any = None
    # missing key passes the check (=(key) equality anchors fold this in)
    missing_ok: bool = False


@dataclass(frozen=True)
class CondCheck:
    """One compiled deny/precondition condition.

    Two modes (semantics: kyverno_tpu/engine/operators.py, reference:
    pkg/engine/variables/operator/*.go):
      A — ``gather`` key vs constant ``values`` (the common shape);
      B — constant ``key_const`` vs a ``value_gather`` projection
          (foreach conditions like ``key: ALL, value: {{element...}}``).
    ``op`` is the lower-cased reference operator name.  ``list_value``
    records whether the constant side was a YAML list — the reference
    dispatches on the operand's type, not just its contents.
    """
    gather: Optional[Any]        # GatherSlot | ElemGather (mode A key)
    op: str                      # 'anyin' | 'allin' | 'anynotin' | 'allnotin'
                                 # | 'equals' | 'notequals' | numeric cmps
    values: Tuple[Any, ...] = ()
    list_value: bool = False
    key_const: Any = None        # mode B constant key
    value_gather: Optional[Any] = None  # mode B value projection


@dataclass(frozen=True)
class BoolExpr:
    """AND/OR/NOT tree over leaves / condition checks (Kleene 3-valued on
    device: each node evaluates to (true-known, false-known)).

    'any_elem' / 'all_elem' quantify their single child over the valid
    elements of the array at ``slot`` (one depth level deeper); a missing
    or null array is vacuous (∃ → False, ∀ → True), mirroring the PSS
    library's ``spec.get(field) or []`` walks (pss/checks.py)."""
    kind: str   # 'leaf' | 'cond' | 'and' | 'or' | 'not' | *_elem
    leaf: Optional[Leaf] = None
    cond: Optional[CondCheck] = None
    children: Tuple['BoolExpr', ...] = ()
    slot: Optional[Slot] = None    # quantifier array slot

    @staticmethod
    def of(leaf: Leaf) -> 'BoolExpr':
        return BoolExpr('leaf', leaf=leaf)

    @staticmethod
    def of_cond(cond: CondCheck) -> 'BoolExpr':
        return BoolExpr('cond', cond=cond)

    @staticmethod
    def all(children: List['BoolExpr']) -> 'BoolExpr':
        if len(children) == 1:
            return children[0]
        return BoolExpr('and', children=tuple(children))

    @staticmethod
    def any(children: List['BoolExpr']) -> 'BoolExpr':
        if len(children) == 1:
            return children[0]
        return BoolExpr('or', children=tuple(children))

    @staticmethod
    def negate(child: 'BoolExpr') -> 'BoolExpr':
        return BoolExpr('not', children=(child,))


# --- status expressions -----------------------------------------------------

@dataclass(frozen=True)
class StatusExpr:
    """Tri-state node mirroring one step of the validate walk.

    kinds and semantics (reference: pkg/engine/validate/validate.go +
    pkg/engine/anchor/handlers.go):

      const     — constant status (operand = status code)
      leaf      — BoolExpr ``expr``: True → PASS, False → FAIL
      seq       — children in walk order; first non-PASS child decides
      cond      — (k) condition anchor: key absent → SKIP; present and
                  ``sub`` non-PASS → SKIP; else PASS   (handlers.go:31)
      global    — <(k): key absent → PASS; present and ``sub`` non-PASS →
                  SKIP                                  (handlers.go:??)
      equality  — =(k): key absent → PASS; else ``sub`` status as-is
      negation  — X(k): key present → FAIL; absent → PASS
      exists    — ^(k): key absent → PASS; non-array → FAIL; else at least
                  one element with ``sub``==PASS → PASS else FAIL
                  (handlers.go:228; inner skips count as non-match)
      forall    — array-of-maps walk (validate.go:218): non-array → FAIL;
                  any element FAIL → FAIL; 0 applied & >0 skips → SKIP;
                  else PASS.  ``sub`` is evaluated per element.
      scalars   — scalar pattern vs array value (validate.go:71 case):
                  non-array handled by plain leaf; for arrays every element
                  must satisfy ``expr``
      deny      — ``expr`` True → FAIL (operand carries nothing)
      precond   — ``expr`` False → SKIP, else PASS (preconditions gate)
      any       — anyPattern: any child PASS → PASS; else all children
                  SKIP → SKIP; else FAIL  (engine.py validate_any_pattern)

    ``slot`` is the anchored key's slot for presence tests (cond/global/
    equality/negation/exists) or the array node slot (forall).
    """
    kind: str
    slot: Optional[Slot] = None
    expr: Optional[BoolExpr] = None
    sub: Optional['StatusExpr'] = None
    children: Tuple['StatusExpr', ...] = ()
    operand: Any = None
    # fail-site id: index into RuleProgram.fail_sites identifying the walk
    # position (path template) the host would report for a FAIL decided at
    # this node; None → a FAIL here is not message-synthesizable on device
    fail_site: Optional[int] = None

    @staticmethod
    def const(status: int) -> 'StatusExpr':
        return StatusExpr('const', operand=status)

    @staticmethod
    def seq(children: List['StatusExpr']) -> 'StatusExpr':
        flat: List[StatusExpr] = []
        for c in children:
            if c.kind == 'seq':
                flat.extend(c.children)
            elif c.kind == 'const' and c.operand == STATUS_PASS:
                continue
            else:
                flat.append(c)
        if not flat:
            return StatusExpr.const(STATUS_PASS)
        if len(flat) == 1:
            return flat[0]
        return StatusExpr('seq', children=tuple(flat))


@dataclass(frozen=True, eq=False)
class RuleProgram:
    """One compiled rule: a status expression per resource."""
    policy_name: str
    rule_name: str
    policy_index: int
    rule_index: int
    status: StatusExpr
    # static pass messages (compile-time constants); anyPattern rules carry
    # one per sub-pattern, indexed by the evaluator's ``detail`` output
    # (reference message format: pkg/engine/validation.go:640)
    pass_messages: Tuple[str, ...]
    # substitution-error messages for unresolvable condition variables,
    # indexed by ``detail`` on STATUS_VAR_ERR (engine.py:388-391,431-434)
    error_messages: Tuple[str, ...] = ()
    # (level, version) for podSecurity rules — synthesized PASS responses
    # carry {'level', 'version', 'checks': []} (engine.py:592-605)
    pss: Optional[Tuple[str, str]] = None
    # static skip message when the rule's SKIP outcome is synthesizable
    # (foreach 'rule skipped', engine.py:628)
    skip_message: Optional[str] = None
    background: bool = True
    # the original rule dict (for host-side match evaluation + fallback)
    rule_raw: Optional[dict] = None
    # --- device FAIL-message synthesis (single-pattern + deny rules) ----
    # fail-site path templates indexed by the evaluator's ``fdet`` output
    # (site = fdet >> 16, element indices in the low bytes); '{e0}'/'{e1}'
    # mark array positions.  None → FAIL cells re-run on the host.
    fail_sites: Optional[Tuple[str, ...]] = None
    # static message prefix: full FAIL message = fail_prefix + path
    # (reference format: pkg/engine/validation.go:722 buildErrorMessage)
    fail_prefix: Optional[str] = None
    # static deny FAIL message (reference: validation.go:460 getDenyMessage);
    # for foreach rules this is the wrapped 'validation failure: …' form
    # (engine.py:665) and is gated on the evaluator's fdet >= 0
    deny_fail_message: Optional[str] = None
    # anyPattern synthesis: per-sub-pattern fail-site tables + the message
    # prefix of buildAnyPatternErrorMessage (validation.go:746); failing
    # children contribute 'rule NAME[i] failed at path P' parts in order
    any_fail_sites: Optional[Tuple[Tuple[str, ...], ...]] = None
    any_fail_prefix: Optional[str] = None
    # context entries (configMap/apiCall/variable) whose VALUES feed no
    # compiled lane: the device decision is context-independent, but the
    # host engine's load-failure semantics must hold — the scanner
    # attempts the load per (resource, rule) and falls back to exact
    # host materialization on failure (reference:
    # pkg/engine/jsonContext.go:126 LoadContext)
    context_spec: Optional[Tuple[dict, ...]] = None
    # the {{...}} inputs the context spec consumes, when all are
    # request.object-rooted: load outcomes are a pure function of these
    # values, so the scanner memoizes per (rule, inputs) instead of
    # re-loading per cell; None -> not cacheable (re-load per resource)
    context_inputs: Optional[Tuple[str, ...]] = None


@dataclass(frozen=True)
class ForEachEntryIR:
    """One compiled ``validate.foreach`` entry (deny-conditions form).

    ``err_gathers`` lists the entry's element gathers in substitution
    order (preconditions doc first, then deny conditions) for the
    per-element variable-error semantics (engine.py:660-667)."""
    list_gather: GatherSlot
    precond: Optional[BoolExpr]
    deny: Optional[BoolExpr]
    err_gathers: Tuple[ElemGather, ...] = ()


@dataclass
class CompiledPolicySet:
    """Output of the compiler for a policy set."""
    slots: List[Slot] = field(default_factory=list)
    slot_index: Dict[Slot, int] = field(default_factory=dict)
    gathers: List[GatherSlot] = field(default_factory=list)
    gather_index: Dict[GatherSlot, int] = field(default_factory=dict)
    elem_gathers: List[ElemGather] = field(default_factory=list)
    elem_gather_index: Dict[ElemGather, int] = field(default_factory=dict)
    programs: List[RuleProgram] = field(default_factory=list)
    # (policy_index, rule dict, policy) for rules the device cannot evaluate
    host_rules: List[Tuple[int, dict, Any]] = field(default_factory=list)
    policies: List[Any] = field(default_factory=list)
    # per-(policy, rule) device/host placement with the attributed
    # fallback reason (observability/coverage.py RulePlacement), in
    # compile order — the compile-time half of the coverage ledger
    placements: List[Any] = field(default_factory=list)

    def slot_id(self, slot: Slot) -> int:
        if slot not in self.slot_index:
            self.slot_index[slot] = len(self.slots)
            self.slots.append(slot)
        return self.slot_index[slot]

    def gather_id(self, g: GatherSlot) -> int:
        if g not in self.gather_index:
            self.gather_index[g] = len(self.gathers)
            self.gathers.append(g)
        return self.gather_index[g]

    def elem_gather_id(self, g: ElemGather) -> int:
        if g not in self.elem_gather_index:
            self.elem_gather_index[g] = len(self.elem_gathers)
            self.elem_gathers.append(g)
        return self.elem_gather_index[g]


class CompileError(Exception):
    """Raised when a rule (or part) cannot be vectorized → host fallback.

    ``reason`` is a stable taxonomy slug (observability/coverage.py
    REASONS) recording WHY the rule left the device path; the default
    covers the common case of an operator / pattern shape outside the
    device vocabulary."""

    def __init__(self, message: str = '',
                 reason: str = 'unsupported_operator'):
        super().__init__(message)
        self.reason = reason
