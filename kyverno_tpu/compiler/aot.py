"""AOT executable codec: serialize compiled evaluators to/from the
persistent store.

The persistent XLA compilation cache only skips the backend compile; a
fresh process still pays ~10s re-tracing the evaluator (the jaxpr for a
full policy pack lowers to ~4MB of StableHLO) plus the cache
deserialize.  Serializing the *compiled executable*
(``jax.experimental.serialize_executable``) keyed by
:func:`kyverno_tpu.aotcache.keys.executable_cache_key` skips trace AND
compile: a second process reaches device-served scans with zero fresh
XLA compiles for a cached policy set.

Blobs are ``codec byte + compressed pickle((payload, in_tree,
out_tree))``; zstandard when available, stdlib zlib otherwise (the
seed's hard zstandard dependency silently disabled the disk path on
hosts without it).  Integrity framing and eviction live one layer down
in :class:`kyverno_tpu.aotcache.store.AotStore` — a corrupt or
stale-codec entry decodes as a miss and is dropped, never raised.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
from typing import Any, Optional

from ..aotcache.keys import executable_cache_key  # noqa: F401 (re-export)
from ..aotcache.store import AotStore, default_store

_log = logging.getLogger('kyverno.aotcache')

_CODEC_ZSTD = b'Z'
_CODEC_ZLIB = b'D'


def _zstd():
    try:
        import zstandard
        return zstandard
    except ImportError:
        return None


def encode_executable(compiled) -> bytes:
    """compiled executable → compressed blob (raises on failure)."""
    from jax.experimental import serialize_executable as se
    payload, in_tree, out_tree = se.serialize(compiled)
    raw = pickle.dumps((payload, in_tree, out_tree))
    zstd = _zstd()
    if zstd is not None:
        return _CODEC_ZSTD + zstd.ZstdCompressor(level=3).compress(raw)
    import zlib
    return _CODEC_ZLIB + zlib.compress(raw, 3)


def decode_executable(blob: bytes) -> Any:
    """blob → loaded executable (raises on any mismatch — callers
    treat that as a miss and drop the entry)."""
    from jax.experimental import serialize_executable as se
    codec, body = blob[:1], blob[1:]
    if codec == _CODEC_ZSTD:
        import zstandard
        raw = zstandard.ZstdDecompressor().decompress(body)
    elif codec == _CODEC_ZLIB:
        import zlib
        raw = zlib.decompress(body)
    else:
        raise ValueError(f'unknown aot codec {codec!r}')
    payload, in_tree, out_tree = pickle.loads(raw)
    return se.deserialize_and_load(payload, in_tree, out_tree)


# -- store orchestration ------------------------------------------------------

def load_executable(key: str, store: Optional[AotStore] = None) -> Any:
    """Loaded executable for ``key`` or None.  A blob that fails to
    decode (stale jax, torn write below the framing's resolution) is
    deleted so the next process recompiles instead of re-failing."""
    store = store or default_store()
    blob = store.load(key)
    if blob is None:
        return None
    try:
        return decode_executable(blob)
    except Exception:  # noqa: BLE001 - stale/corrupt entry: recompile
        _log.warning('aot entry %s undecodable; dropping', key[:12])
        store.delete(key)
        return None


#: in-flight background stores; flush_stores() joins them (tests, and
#: warmers that want the entry on disk before declaring readiness)
_STORE_THREADS: set = set()
_STORE_THREADS_LOCK = threading.Lock()


def store_executable_async(key: str, compiled,
                           store: Optional[AotStore] = None) -> None:
    """Serialize + write in a daemon thread (~40MB compressed for a
    full-pack chunk executable; must not block the scan path)."""
    store = store or default_store()
    if not store.enabled:
        return

    def work():
        try:
            store.put(key, encode_executable(compiled))
        except Exception:  # noqa: BLE001 - cache write is best-effort
            pass
        finally:
            with _STORE_THREADS_LOCK:
                _STORE_THREADS.discard(threading.current_thread())

    t = threading.Thread(target=work, daemon=True,
                         name=f'aot-store-{key[:8]}')
    with _STORE_THREADS_LOCK:
        _STORE_THREADS.add(t)
    t.start()


def flush_stores(timeout: float = 120.0) -> None:
    """Join outstanding background stores (bounded per thread)."""
    with _STORE_THREADS_LOCK:
        threads = list(_STORE_THREADS)
    for t in threads:
        t.join(timeout)


def evict_executable(key: str, store: Optional[AotStore] = None) -> None:
    """Drop a poisoned entry from disk so the next call recompiles."""
    (store or default_store()).delete(key)


def warm_cache_dir() -> Optional[str]:
    """The active store directory (diagnostics / README numbers)."""
    s = default_store()
    return s.root


def aot_enabled() -> bool:
    return default_store().enabled and \
        os.environ.get('KTPU_AOT', '1') == '1'
