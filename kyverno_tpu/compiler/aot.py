"""AOT executable codec: serialize compiled evaluators to/from the
persistent store.

The persistent XLA compilation cache only skips the backend compile; a
fresh process still pays ~10s re-tracing the evaluator (the jaxpr for a
full policy pack lowers to ~4MB of StableHLO) plus the cache
deserialize.  Serializing the *compiled executable*
(``jax.experimental.serialize_executable``) keyed by
:func:`kyverno_tpu.aotcache.keys.executable_cache_key` skips trace AND
compile: a second process reaches device-served scans with zero fresh
XLA compiles for a cached policy set.

Blobs are ``codec byte + compressed pickle((payload, in_tree,
out_tree, meta))``; zstandard when available, stdlib zlib otherwise
(the seed's hard zstandard dependency silently disabled the disk path
on hosts without it).  ``meta`` records the compile-time environment
(host CPU-feature fingerprint, codegen env scope, jax versions):
XLA:CPU AOT artifacts embed the compile machine's instruction-set
features and can SIGILL when loaded on a host missing them — the cache
*key* already scopes on these axes, but containerized fleets can mask
``/proc/cpuinfo`` into a collision, so the load path re-checks the
recorded meta and REJECTS mismatched entries (fresh compile via the
persistent XLA cache instead of a possibly-lethal load), counting each
rejection on ``kyverno_tpu_aot_load_rejected_total{reason}``.
Integrity framing and eviction live one layer down in
:class:`kyverno_tpu.aotcache.store.AotStore` — a corrupt or
stale-codec entry decodes as a miss and is dropped, never raised.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
from typing import Any, Optional, Tuple

from ..aotcache import keys as _keys
from ..aotcache.keys import executable_cache_key  # noqa: F401 (re-export)
from ..aotcache.store import AotStore, default_store

_log = logging.getLogger('kyverno.aotcache')

AOT_LOAD_REJECTED = 'kyverno_tpu_aot_load_rejected_total'

_CODEC_ZSTD = b'Z'
_CODEC_ZLIB = b'D'


def _zstd():
    try:
        import zstandard
        return zstandard
    except ImportError:
        return None


def _compile_meta() -> dict:
    """The environment axes an executable is only loadable under."""
    import jax
    return {
        'host_features': _keys.host_fingerprint(),
        'env_scope': repr(_keys.env_scope()),
        'jax': (jax.__version__, jax.lib.__version__),
    }


def encode_executable(compiled) -> bytes:
    """compiled executable → compressed blob (raises on failure)."""
    from jax.experimental import serialize_executable as se
    payload, in_tree, out_tree = se.serialize(compiled)
    return _pack_blob(payload, in_tree, out_tree, _compile_meta())


def _pack_blob(payload, in_tree, out_tree, meta: dict) -> bytes:
    raw = pickle.dumps((payload, in_tree, out_tree, meta))
    zstd = _zstd()
    if zstd is not None:
        return _CODEC_ZSTD + zstd.ZstdCompressor(level=3).compress(raw)
    import zlib
    return _CODEC_ZLIB + zlib.compress(raw, 3)


def _unpack_blob(blob: bytes) -> Tuple[Any, Any, Any, dict]:
    """blob → (payload, in_tree, out_tree, meta); raises on any codec
    or framing mismatch (callers treat that as ``undecodable``)."""
    codec, body = blob[:1], blob[1:]
    if codec == _CODEC_ZSTD:
        import zstandard
        raw = zstandard.ZstdDecompressor().decompress(body)
    elif codec == _CODEC_ZLIB:
        import zlib
        raw = zlib.decompress(body)
    else:
        raise ValueError(f'unknown aot codec {codec!r}')
    parts = pickle.loads(raw)
    if len(parts) == 3:  # pre-meta frame: treat as stale
        raise ValueError('legacy aot frame without compile meta')
    return parts


def _meta_mismatch(meta: dict) -> Optional[str]:
    """Rejection reason when ``meta`` does not match this process."""
    import jax
    current = {
        'host_features': ('feature_mismatch', _keys.host_fingerprint()),
        'env_scope': ('env_mismatch', repr(_keys.env_scope())),
        'jax': ('jax_mismatch',
                (jax.__version__, jax.lib.__version__)),
    }
    for field, (reason, want) in current.items():
        got = meta.get(field)
        if got is None:
            continue  # older frame missing this axis: key scoping holds
        if isinstance(want, tuple):
            got = tuple(got)
        if got != want:
            return reason
    return None


def decode_executable(blob: bytes) -> Any:
    """blob → loaded executable (raises on any mismatch — callers
    treat that as a miss and drop the entry)."""
    from jax.experimental import serialize_executable as se
    payload, in_tree, out_tree, _meta = _unpack_blob(blob)
    return se.deserialize_and_load(payload, in_tree, out_tree)


# -- store orchestration ------------------------------------------------------

def _count_rejection(reason: str) -> None:
    from ..observability.metrics import global_registry
    reg = global_registry()
    if reg is not None:
        reg.inc(AOT_LOAD_REJECTED, reason=reason)


def _reject(store: AotStore, key: str, reason: str) -> None:
    """Drop an unloadable entry and account for it: the caller falls
    back to a fresh compile (persistent-XLA-cache assisted), which is
    always safe — a forced load of a feature-mismatched executable can
    SIGILL the process."""
    from ..observability import executables
    _log.warning('aot entry %s rejected at load (%s); dropping',
                 key[:12], reason)
    store.delete(key)
    _count_rejection(reason)
    executables.record_eviction(key, reason)


def load_executable(key: str, store: Optional[AotStore] = None) -> Any:
    """Loaded executable for ``key`` or None.  A blob that fails to
    decode (stale jax, torn write below the framing's resolution), was
    compiled under a different CPU-feature set / codegen env, or fails
    XLA deserialization is deleted and counted on
    ``aot_load_rejected_total`` so the next process recompiles instead
    of re-failing (or worse, SIGILLing mid-request)."""
    from jax.experimental import serialize_executable as se
    from .. import faults
    store = store or default_store()
    blob = store.load(key)
    if blob is None:
        return None
    try:
        # injected aot_load faults exercise the real rejection path: a
        # load that dies mid-decode counts a rejection and recompiles
        faults.check(faults.SITE_AOT_LOAD)
        payload, in_tree, out_tree, meta = _unpack_blob(blob)
    except Exception:  # noqa: BLE001 - stale/corrupt entry: recompile
        _reject(store, key, 'undecodable')
        return None
    reason = _meta_mismatch(meta if isinstance(meta, dict) else {})
    if reason is not None:
        _reject(store, key, reason)
        return None
    try:
        return se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception:  # noqa: BLE001 - backend refused the artifact
        _reject(store, key, 'deserialize_failed')
        return None


#: in-flight background stores; flush_stores() joins them (tests, and
#: warmers that want the entry on disk before declaring readiness)
_STORE_THREADS: set = set()
_STORE_THREADS_LOCK = threading.Lock()


def store_executable_async(key: str, compiled,
                           store: Optional[AotStore] = None) -> None:
    """Serialize + write in a daemon thread (~40MB compressed for a
    full-pack chunk executable; must not block the scan path)."""
    store = store or default_store()
    if not store.enabled:
        return

    def work():
        try:
            store.put(key, encode_executable(compiled))
        except Exception:  # noqa: BLE001 - cache write is best-effort
            pass
        finally:
            with _STORE_THREADS_LOCK:
                _STORE_THREADS.discard(threading.current_thread())

    t = threading.Thread(target=work, daemon=True,
                         name=f'aot-store-{key[:8]}')
    with _STORE_THREADS_LOCK:
        _STORE_THREADS.add(t)
    t.start()


def flush_stores(timeout: float = 120.0) -> None:
    """Join outstanding background stores (bounded per thread)."""
    with _STORE_THREADS_LOCK:
        threads = list(_STORE_THREADS)
    for t in threads:
        t.join(timeout)


def evict_executable(key: str, store: Optional[AotStore] = None,
                     reason: Optional[str] = None) -> None:
    """Drop a poisoned entry from disk so the next call recompiles.
    ``reason`` (e.g. ``execute_failed`` for artifacts that loaded but
    died at dispatch — the machine-feature SIGILL class) also counts
    the eviction on ``aot_load_rejected_total``."""
    (store or default_store()).delete(key)
    if reason is not None:
        from ..observability import executables
        _count_rejection(reason)
        executables.record_eviction(key, reason)


def warm_cache_dir() -> Optional[str]:
    """The active store directory (diagnostics / README numbers)."""
    s = default_store()
    return s.root


def aot_enabled() -> bool:
    return default_store().enabled and \
        os.environ.get('KTPU_AOT', '1') == '1'
